#!/usr/bin/env python
"""Serving chaos harness: open-loop load against a live ``serve_game``
under seeded fault plans — the serving twin of ``chaos_sweep.py``.

``chaos_sweep.py`` proves training survives injected faults with model
quality intact; this tool proves the REQUEST PATH survives them with its
books intact. For every ``(seed, rate)`` cell it activates a randomized-
but-seeded ``FaultPlan`` over the serving injection sites
(``serving.execute`` fails scoring AND ranking calls, ``serving.parse``
fails request parses) and drives MIXED open-loop load — every 4th
request is a ``GET /rank`` (``bench_serving.mixed_open_loop_run``, the
coordinated-omission-proof generator) — against an in-process
rank-enabled server, asserting:

- **accounting identity, per kind**: every offered request is accounted
  for exactly once — ``shed + served + errored == offered`` for the
  score AND the rank books independently — and the client-observed shed
  total matches the server's ``photon_shed_total`` delta;
- **no stranded futures**: after the load drains, the microbatcher queue
  is empty, its worker is alive, and a fresh request scores promptly
  (``/readyz`` agrees);
- **error-rate ceiling**: injected faults fail individual requests, they
  never amplify past ``--error-ceiling`` of offered traffic (a batch
  fault fails one microbatch, not the worker);
- **incumbent-keeps-serving**: across an injected ``serving.reload``
  fault the ``/reload`` returns 409 and the active version's scores stay
  BIT-IDENTICAL before/after — delivery faults never corrupt serving;
  a pinned ``/rank`` probe's ids+scores must survive every load cell
  unchanged too (an execute fault fails a rank microbatch, never the
  worker or the tables).

A failing cell reproduces exactly: the printed plan JSON IS the repro
(``PHOTON_FAULT_PLAN='<plan>' python -m photon_ml_tpu serve_game ...``).

Budgets::

    --budget smoke   1 seed x 1 rate, small load   (the tier-1 invocation)
    --budget full    the full --seeds x --rates grid (nightly)

Exit code: 0 = every cell passed, 1 = failures (listed last).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _p in (REPO, os.path.join(REPO, "tools")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

import bench_serving  # noqa: E402
import chaos_sweep  # noqa: E402


def train_model(tmp: str, rows: int) -> tuple[str, str]:
    """Tiny mixed-effect GAME model (the chaos_sweep dataset shape) →
    (model output dir, training avro path — reused as the request pool)."""
    from photon_ml_tpu.cli import train_game

    train = os.path.join(tmp, "train.avro")
    chaos_sweep.write_dataset(train, rows, seed=3)
    out = os.path.join(tmp, "model")
    train_game.run([
        "--training-data", train,
        "--output-dir", out,
        "--feature-shards", chaos_sweep.SHARDS,
        "--coordinates", *chaos_sweep.COORDS,
        "--update-sequence", "global,perUser",
        "--grid", "global=0.1", "perUser=1",
        "--evaluators", "",
    ])
    return out, train


def build_plan(seed: int, rate: float) -> dict:
    """One seeded symmetric plan over the request-path sites. Parse
    faults fire at a quarter of the execute rate (a parse fault fails one
    request; an execute fault fails a whole microbatch)."""
    return {"seed": seed, "specs": [
        {"site": "serving.execute", "rate": rate},
        {"site": "serving.parse", "rate": rate / 4},
    ]}


def scraped_shed_total(base: str) -> float:
    """Sum of ``photon_shed_total`` across reasons, from ``/metrics``."""
    snapshot = bench_serving._scrape_metrics(base)
    return sum(v for _labels, v in
               (snapshot or {}).get("photon_shed_total", []))


def settle(server, base: str, timeout_s: float = 10.0) -> dict:
    """Wait for the post-load queue to drain; returns the final /readyz
    body. The in-process handles let the stranded-future check be exact:
    queue depth straight from the batcher, worker liveness from its
    death flag."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if server.service.batcher.queue_depth() == 0:
            break
        time.sleep(0.05)
    return bench_serving._http_json(base + "/readyz")


def run_fleet_chaos(args) -> int:
    """``--fleet``: the fleet-router chaos cells (ISSUEs 15 + 16). An
    N=2 entity-sharded fleet (cli/serve_fleet.py) under six failure
    shapes, each asserting the books and the bit-parity pins:

    - **fanout-fault**: seeded ``fleet.fanout`` faults during mixed
      open-loop load — per-kind ``served + shed + errored == offered``,
      no served response EVER carries a second model lineage, probe
      scores + top-k bit-identical after the storm;
    - **host-kill**: one host stopped mid-load (the real crash shape) —
      the identity still holds (lost-shard traffic becomes typed 503s,
      counted as errors), and after restarting the host on its port the
      fleet's probe scores are bit-identical to the pinned ones;
    - **two-phase-abort**: an injected ``serving.reload`` fault fails ONE
      host's prepare — the epoch aborts (409), every host's version and
      the probe scores are untouched;
    - **hot-shard**: an open-loop storm of records that ALL live on one
      shard — overload stays isolated: a concurrent cold-shard prober
      keeps serving bit-identical scores with zero failures while the
      hot shard sheds;
    - **reshard-under-traffic**: ``POST /reshard`` fired mid-load —
      zero client-visible errors, every served response stamped with
      the incumbent or the candidate map hash (never a mixed one), the
      repack counters prove only the reassigned buckets' rows moved
      (O(moved), not a full repack), probes bit-identical across the
      map swap;
    - **replica-kill**: a fresh R=2 fleet (``--replicas 2``) serving
      bit-identically to the R=1 one; one replica stopped mid-load —
      ZERO client-visible errors (the surviving replica absorbs every
      leg, ``photon_fleet_replica_retries_total`` moves), probes
      bit-identical, every surviving batcher worker alive;
    - **flight-dump**: a retained-plane fleet (``--flight-dir`` +
      ``--history-period-s``) with one host killed mid-load while a
      seeded ``fleet.fanout`` fault trips the fault-site trigger — the
      black box must publish a COMPLETE parseable dump atomically (no
      ``.tmp`` survivor), and ``tools/postmortem.py`` must render it
      byte-deterministically, reconstructing the final shard-map
      generation, model lineage and last admitted request ids.
    """
    import threading

    from photon_ml_tpu.cli import serve_fleet, serve_game
    from photon_ml_tpu.resilience import FaultPlan, injected
    from photon_ml_tpu.resilience.retry import (
        get_default_policy,
        set_default_policy,
    )

    requests = min(args.requests, 150) if args.budget == "smoke" \
        else args.requests
    rate = float(args.rates.split(",")[0])
    cells: list[dict] = []
    failures: list[str] = []
    prev_policy = get_default_policy()
    with tempfile.TemporaryDirectory() as tmp:
        model_dir, train_path = train_model(tmp, args.rows)
        set_default_policy(prev_policy)
        fleet = serve_fleet.build_fleet([
            "--model-dir", model_dir,
            "--feature-shards", chaos_sweep.SHARDS,
            "--port", "0", "--fleet-shards", "2",
            "--microbatch", "8", "--max-wait-ms", "1",
            "--max-queue", str(args.max_queue),
            "--rank-item-coordinate", "perUser", "--rank-max-k", "16",
        ])
        base = fleet.url
        bench_serving.wait_ready(base)
        from photon_ml_tpu.io.avro import iter_avro_file

        pool = list(iter_avro_file(train_path))[:256]
        users = list(dict.fromkeys(
            (rec.get("metadataMap") or {}).get("userId", "u0")
            for rec in pool))
        probe = {"records": pool[:5]}
        probe_scores = bench_serving._http_json(
            base + "/score", probe)["scores"]
        probe_rank_url = bench_serving.rank_url(base, users[0], 5)

        def canon_rank(body):
            # per-item scores are the bit-identity claim; ORDER among
            # exactly tied scores is placement-dependent (a reshard
            # legitimately reorders ties across shards) — canonicalize
            return sorted(zip(body["ids"], body["scores"]),
                          key=lambda p: (-p[1], str(p[0])))

        probe_topk = canon_rank(bench_serving._http_json(probe_rank_url))
        print(f"[chaos-serving] fleet up at {base} "
              f"(hosts: {', '.join(fleet.host_urls())}), probes pinned",
              flush=True)

        def run_mixed(n):
            return bench_serving.mixed_open_loop_run(
                base, pool, users, [1], target_qps=args.target_qps,
                requests=n, ks=(3, 8), rank_every=4)

        def check_books(cell, run, ceiling, allowed_maps=None):
            problems = []
            for kind in ("score", "rank"):
                b = run[kind]
                if (len(b["corrected_ms"]) + b["reconnected"] + b["shed"]
                        + len(b["errors"]) != b["offered"]):
                    problems.append(f"{kind} accounting broke: {b}")
                if len(b["lineages"]) > 1:
                    problems.append(
                        f"{kind} responses MIXED lineages: "
                        f"{sorted(b['lineages'])}")
                maps = b.get("shard_maps", set())
                if allowed_maps is None and len(maps) > 1:
                    problems.append(
                        f"{kind} responses MIXED shard maps: "
                        f"{sorted(maps)}")
                elif allowed_maps is not None and maps - allowed_maps:
                    problems.append(
                        f"{kind} responses carried unexpected shard "
                        f"maps: {sorted(maps - allowed_maps)}")
            errored = sum(len(run[k]["errors"]) for k in ("score", "rank"))
            if errored > ceiling * run["offered"]:
                problems.append(f"error rate {errored / run['offered']:.3f}"
                                f" > ceiling {ceiling}")
            cell.update(
                offered=run["offered"],
                served=sum(len(run[k]["corrected_ms"])
                           + run[k]["reconnected"]
                           for k in ("score", "rank")),
                shed=sum(run[k]["shed"] for k in ("score", "rank")),
                errored=errored)
            return problems

        def check_probes(problems):
            after = bench_serving._http_json(base + "/score", probe)
            if after["scores"] != probe_scores:
                problems.append("probe scores changed")
            rank_after = bench_serving._http_json(probe_rank_url)
            if canon_rank(rank_after) != probe_topk:
                problems.append("probe top-k changed")

        try:
            # --- cell 1: injected fan-out faults under mixed load -------
            plan_obj = {"seed": 0,
                        "specs": [{"site": "fleet.fanout", "rate": rate}]}
            cell = {"cell": "fanout-fault", "plan": plan_obj}
            with injected(FaultPlan.from_json(plan_obj)):
                run = run_mixed(requests)
            # a faulted leg fails the whole fan-out (typed 503) and
            # /score legs can fan 2-wide — the ceiling doubles the rate,
            # plus parse-noise headroom like the single-host grid
            problems = check_books(cell, run, max(args.error_ceiling,
                                                  4 * rate))
            # scrape-through-faults: the router's folded /metrics visits
            # the SAME fleet.fanout site once per host scrape leg, so a
            # rate-1.0 plan faults EVERY scrape deterministically — the
            # fold must still answer (router-only partial fold, never a
            # 500) and annotate the losses per host
            with injected(FaultPlan.from_json(
                    {"seed": 0, "specs": [{"site": "fleet.fanout",
                                           "rate": 1.0}]})):
                snap_lost = bench_serving._scrape_metrics(base)
            if snap_lost is None:
                problems.append("router /metrics failed with every host "
                                "scrape faulted (partial fold must be "
                                "served, never a 500)")
            elif not sum(v for _labels, v in snap_lost.get(
                    "photon_fleet_scrape_errors_total", [])):
                problems.append("faulted scrapes left photon_fleet_"
                                "scrape_errors_total at 0")
            check_probes(problems)
            cell["ok"] = not problems
            cells.append(cell)
            print(f"[chaos-serving] fleet fanout-fault: "
                  f"offered={run['offered']} served={cell['served']} "
                  f"errored={cell['errored']} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet fanout-fault: " + "; ".join(problems)
                                + f" — repro with PHOTON_FAULT_PLAN="
                                  f"'{json.dumps(plan_obj)}'")

            # --- cell 2: kill one host mid-load, then restart it --------
            cell = {"cell": "host-kill"}
            # capacity-plane baseline: the survivor's open-connection
            # gauge before the storm (its own /healthz socket included,
            # so the post-recovery read is like-for-like)
            survivor_url = fleet.hosts[0].url
            conn_before = bench_serving._http_json(
                survivor_url + "/healthz")["connections"]["open"]
            victim = fleet.hosts[1]
            victim_port = victim.port
            killer = threading.Timer(
                0.25 * requests / args.target_qps, victim.stop)
            killer.start()
            run = run_mixed(requests)
            killer.join()
            # losing one of two shards costs up to ~all rank traffic and
            # the dead shard's score traffic — the identity is the claim,
            # not a low error rate
            problems = check_books(cell, run, 1.0)
            restarted = serve_game.build_server([
                "--model-dir", model_dir,
                "--feature-shards", chaos_sweep.SHARDS,
                "--port", str(victim_port),
                "--microbatch", "8", "--max-wait-ms", "1",
                "--max-queue", str(args.max_queue),
                "--rank-item-coordinate", "perUser", "--rank-max-k", "16",
                "--brownout-poll-s", "0",
                "--fleet-shard", "1", "--fleet-shard-count", "2",
            ]).start()
            fleet.hosts[1] = restarted
            bench_serving.wait_ready(base)
            check_probes(problems)  # bit-identical across kill + restart
            ready = bench_serving._http_json(base + "/readyz")
            if not ready["ready"]:
                problems.append(f"fleet not ready after restart: {ready}")
            # capacity plane under chaos: once the load stops, every
            # live host's connection books must balance (accepted ==
            # closed + open is a single-lock snapshot identity) and the
            # survivor's open-connection gauge must drain back to its
            # pre-kill baseline — leaked sockets would show up here
            deadline = time.monotonic() + 10.0
            conn_after = None
            while time.monotonic() < deadline:
                conn_after = bench_serving._http_json(
                    survivor_url + "/healthz")["connections"]["open"]
                if conn_after <= conn_before:
                    break
                time.sleep(0.2)
            if conn_after is None or conn_after > conn_before:
                problems.append(
                    f"open-connection gauge did not return to its "
                    f"pre-kill baseline ({conn_after} > {conn_before})")
            for live in fleet.hosts:
                stats = bench_serving._http_json(
                    live.url + "/healthz")["connections"]
                if stats["accepted"] != stats["closed"] + stats["open"]:
                    problems.append(
                        f"connection accounting identity broke on "
                        f"{live.url}: {stats}")
            cell["ok"] = not problems
            cells.append(cell)
            print(f"[chaos-serving] fleet host-kill: "
                  f"offered={run['offered']} served={cell['served']} "
                  f"errored={cell['errored']} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet host-kill: " + "; ".join(problems))

            # --- cell 3: two-phase abort (one host refuses prepare) -----
            reload_plan = {"seed": 0,
                           "specs": [{"site": "serving.reload", "at": [0]}]}
            cell = {"cell": "two-phase-abort", "plan": reload_plan}
            versions0 = [bench_serving._http_json(u + "/healthz")["version"]
                         for u in fleet.host_urls()]
            status = None
            with injected(FaultPlan.from_json(reload_plan)):
                try:
                    bench_serving._http_json(base + "/reload",
                                             {"model_dir": model_dir})
                    status = 200
                except Exception as e:
                    status = getattr(e, "code", None)
            versions1 = [bench_serving._http_json(u + "/healthz")["version"]
                         for u in fleet.host_urls()]
            problems = []
            if status != 409:
                problems.append(f"faulted two-phase reload returned "
                                f"{status}, want 409")
            if versions1 != versions0:
                problems.append(f"active versions moved {versions0} → "
                                f"{versions1} across an aborted epoch")
            check_probes(problems)
            cell.update(reload_status=status, versions=versions1,
                        ok=not problems)
            cells.append(cell)
            print(f"[chaos-serving] fleet two-phase-abort: status={status} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet two-phase-abort: "
                                + "; ".join(problems))

            # --- cell 4: hot-shard storm, cold shard unharmed ------------
            cell = {"cell": "hot-shard"}
            smap = fleet.router.shard_map

            def user_of(rec):
                return (rec.get("metadataMap") or {}).get("userId", "u0")

            by_shard: dict = {0: [], 1: []}
            for rec in pool:
                by_shard[smap.shard_of(user_of(rec))].append(rec)
            hot = max(by_shard, key=lambda s: len(by_shard[s]))
            hot_pool, cold_pool = by_shard[hot], by_shard[1 - hot]
            problems = []
            if not hot_pool or not cold_pool:
                problems.append(f"degenerate pool split "
                                f"({len(hot_pool)}/{len(cold_pool)})")
            else:
                cold_probe = {"records": cold_pool[:5]}
                cold_scores = bench_serving._http_json(
                    base + "/score", cold_probe)["scores"]
                stop_evt = threading.Event()
                cold_book = {"served": 0, "failed": []}

                def cold_prober():
                    # the isolation witness: a cold-shard request stream
                    # concurrent with the storm — it must keep serving
                    # the pinned scores, never shed or error
                    while not stop_evt.is_set():
                        try:
                            got = bench_serving._http_json(
                                base + "/score", cold_probe,
                                timeout=10)["scores"]
                            if got != cold_scores:
                                cold_book["failed"].append(
                                    "cold scores moved")
                            else:
                                cold_book["served"] += 1
                        except Exception as e:
                            cold_book["failed"].append(repr(e))
                        stop_evt.wait(0.02)

                prober = threading.Thread(target=cold_prober)
                prober.start()
                try:
                    run = bench_serving.mixed_open_loop_run(
                        base, hot_pool, users, [4],
                        target_qps=max(4 * args.target_qps, 800.0),
                        requests=requests, rank_every=0)
                finally:
                    stop_evt.set()
                    prober.join()
                problems += check_books(cell, run, args.error_ceiling)
                if not cold_book["served"]:
                    problems.append("no cold-shard probe served during "
                                    "the storm")
                if cold_book["failed"]:
                    problems.append(f"cold shard took collateral damage: "
                                    f"{cold_book['failed'][:3]}")
                check_probes(problems)
                cell.update(hot_shard=hot, hot_shed=run["score"]["shed"],
                            cold_probes_served=cold_book["served"])
            cell["ok"] = not problems
            cells.append(cell)
            print(f"[chaos-serving] fleet hot-shard: "
                  f"shed={cell.get('hot_shed')} "
                  f"cold_served={cell.get('cold_probes_served')} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet hot-shard: " + "; ".join(problems))

            # --- cell 5: live reshard under open-loop traffic ------------
            from photon_ml_tpu.fleet.sharding import bucket_of_id

            incumbent = fleet.router.shard_map
            all_ids = set()
            for h in fleet.hosts:
                for store in h.service.registry.active().stores.values():
                    all_ids.update(str(i) for i in store.row_of_id)
            # move the buckets that actually hold a donor shard's rows
            # (plus that shard's first few empty ones) — a meaningful
            # O(moved) assertion needs moved > 0 on the tiny model
            donor = max(range(2), key=lambda s: sum(
                1 for i in all_ids if incumbent.shard_of(i) == s))
            donors = sorted({bucket_of_id(i) for i in all_ids
                             if incumbent.shard_of(i) == donor})
            donors += [b for b, s in enumerate(incumbent.buckets)
                       if s == donor and b not in set(donors)][:16]
            moves = {str(b): 1 - donor for b in donors}
            moved_set = set(donors)
            expected_moved = sum(1 for i in all_ids
                                 if bucket_of_id(i) in moved_set)
            cell = {"cell": "reshard-under-traffic",
                    "moved_buckets": len(moves),
                    "expected_moved_rows": expected_moved}
            resp_box: dict = {}

            def fire_reshard():
                try:
                    resp_box["resp"] = bench_serving._http_json(
                        base + "/reshard", {"moves": moves})
                except Exception as e:
                    resp_box["error"] = repr(e)

            resharder = threading.Timer(
                0.25 * requests / args.target_qps, fire_reshard)
            resharder.start()
            run = run_mixed(requests)
            resharder.join()
            problems = []
            if not expected_moved:
                problems.append("degenerate reshard: no rows in the "
                                "moved buckets")
            resp = resp_box.get("resp")
            if resp is None:
                problems.append(f"/reshard failed: {resp_box.get('error')}")
            # a reshard epoch mid-load costs ZERO client-visible errors;
            # every served response carries the incumbent or the candidate
            # map hash, never anything else
            allowed = {incumbent.map_hash} | (
                {resp["shard_map"]} if resp else set())
            problems += check_books(cell, run, 0.0, allowed_maps=allowed)
            if resp is not None:
                if resp["moved_buckets"] != len(moves):
                    problems.append(
                        f"moved {resp['moved_buckets']} buckets, "
                        f"want {len(moves)}")
                m = resp["moved"]
                # O(moved): exactly the reassigned buckets' rows repack —
                # in == out == the rows living in the moved buckets, and
                # everything else stays put
                if (m["moved_in"] != expected_moved
                        or m["moved_out"] != expected_moved):
                    problems.append(
                        f"repack not O(moved): counters {m}, want "
                        f"{expected_moved} rows in both directions")
                if m["retained"] != len(all_ids) - expected_moved:
                    problems.append(
                        f"retained {m['retained']} rows, want "
                        f"{len(all_ids) - expected_moved}")
                hz = bench_serving._http_json(base + "/healthz")
                if hz["shard_map"]["hash"] != resp["shard_map"]:
                    problems.append(
                        f"router map {hz['shard_map']['hash']} != "
                        f"activated {resp['shard_map']}")
                if hz["shard_map"].get("mixed"):
                    problems.append("hosts disagree on the shard map "
                                    "after activation")
                cell.update(shard_map=resp["shard_map"],
                            moved=m, map_version=resp["map_version"])
            check_probes(problems)  # bit-identical across the map swap
            cell["ok"] = not problems
            cells.append(cell)
            print(f"[chaos-serving] fleet reshard-under-traffic: "
                  f"moved={cell.get('moved')} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet reshard-under-traffic: "
                                + "; ".join(problems))

            # no stranded work anywhere: every host's batcher workers
            # must have survived all five cells
            for i, h in enumerate(fleet.hosts):
                for name, b in (("batcher", h.service.batcher),
                                ("rank batcher", h.service.rank_batcher)):
                    if b is not None and b.dead is not None:
                        failures.append(
                            f"fleet host {i} {name} worker died: "
                            f"{b.dead!r}")
        finally:
            fleet.stop()

        # --- cell 6: replica-kill on an R=2 fleet (fleet.replica) --------
        cell = {"cell": "replica-kill"}
        fleet2 = serve_fleet.build_fleet([
            "--model-dir", model_dir,
            "--feature-shards", chaos_sweep.SHARDS,
            "--port", "0", "--fleet-shards", "2", "--replicas", "2",
            "--microbatch", "8", "--max-wait-ms", "1",
            "--max-queue", str(args.max_queue),
            "--rank-item-coordinate", "perUser", "--rank-max-k", "16",
        ])
        base2 = fleet2.url
        try:
            bench_serving.wait_ready(base2)
            problems = []
            # replication is invisible to scores: the R=2 fleet answers
            # bit-identically to the R=1 probes pinned above
            got = bench_serving._http_json(base2 + "/score",
                                           probe)["scores"]
            if got != probe_scores:
                problems.append("R=2 probe scores differ from the "
                                "R=1 fleet")
            rank2 = bench_serving._http_json(
                bench_serving.rank_url(base2, users[0], 5))
            if canon_rank(rank2) != probe_topk:
                problems.append("R=2 probe top-k differs from the "
                                "R=1 fleet")
            snap0 = bench_serving._scrape_metrics(base2) or {}
            retries0 = sum(v for _l, v in snap0.get(
                "photon_fleet_replica_retries_total", []))
            victim = fleet2.hosts[1]  # shard 0, replica 1
            killer = threading.Timer(
                0.25 * requests / args.target_qps, victim.stop)
            killer.start()
            run = bench_serving.mixed_open_loop_run(
                base2, pool, users, [1], target_qps=args.target_qps,
                requests=requests, ks=(3, 8), rank_every=4)
            killer.join()
            # the replica group absorbs the kill: ZERO client-visible
            # errors (no 503 reason=upstream), not merely a bounded rate
            problems += check_books(cell, run, 0.0)
            for kind in ("score", "rank"):
                if run[kind]["errors"]:
                    problems.append(
                        f"{kind} errors leaked past the replica group: "
                        f"{run[kind]['errors'][:3]}")
            ready = bench_serving._http_json(base2 + "/readyz")
            if not ready["ready"]:
                problems.append(f"fleet not ready with a replica down: "
                                f"{ready}")
            snap1 = bench_serving._scrape_metrics(base2) or {}
            retries = sum(v for _l, v in snap1.get(
                "photon_fleet_replica_retries_total", [])) - retries0
            if retries <= 0:
                problems.append("no replica retries recorded across "
                                "the kill")
            got = bench_serving._http_json(base2 + "/score",
                                           probe)["scores"]
            if got != probe_scores:
                problems.append("probe scores moved across the "
                                "replica kill")
            for i, h in enumerate(fleet2.hosts):
                if h is victim:
                    continue
                for name, b in (("batcher", h.service.batcher),
                                ("rank batcher", h.service.rank_batcher)):
                    if b is not None and b.dead is not None:
                        problems.append(f"host {i} {name} worker died: "
                                        f"{b.dead!r}")
            cell.update(replica_retries=retries, ok=not problems)
            cells.append(cell)
            print(f"[chaos-serving] fleet replica-kill: "
                  f"offered={run['offered']} "
                  f"retries={retries} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet replica-kill: "
                                + "; ".join(problems))
        finally:
            fleet2.stop()
            set_default_policy(prev_policy)

        # --- cell 7: black box survives a mid-load host kill -------------
        # a retained-plane fleet under traffic, one host killed mid-load
        # while a seeded fleet.fanout fault trips the fault-site dump
        # trigger: the flight dump must publish ATOMICALLY (complete
        # parseable JSONL, no .tmp), and the postmortem page must be
        # byte-deterministic AND reconstruct the fleet's final shard-map
        # generation, model lineage and last admitted request ids
        import postmortem

        cell = {"cell": "flight-dump"}
        flight_dir = os.path.join(tmp, "flight")
        fleet3 = serve_fleet.build_fleet([
            "--model-dir", model_dir,
            "--feature-shards", chaos_sweep.SHARDS,
            "--port", "0", "--fleet-shards", "2",
            "--microbatch", "8", "--max-wait-ms", "1",
            "--max-queue", str(args.max_queue),
            "--history-period-s", "0.1", "--history-capacity", "64",
            "--flight-dir", flight_dir,
        ])
        base3 = fleet3.url
        try:
            bench_serving.wait_ready(base3)
            problems = []
            statusz = bench_serving._http_json(base3 + "/statusz")
            map_version = statusz["shard_map"]["version"]
            lineages = [str(h.get("lineage")) for h in statusz["hosts"]
                        if h.get("lineage")]
            victim = fleet3.hosts[1]
            killer = threading.Timer(
                0.25 * requests / args.target_qps, victim.stop)
            killer.start()
            with injected(FaultPlan.from_json(
                    {"seed": 0, "specs": [{"site": "fleet.fanout",
                                           "rate": 0.05}]})):
                run = bench_serving.mixed_open_loop_run(
                    base3, pool, users, [1],
                    target_qps=args.target_qps, requests=requests,
                    rank_every=0)
            killer.join()
            # losing a shard mid-load makes errors legitimate — the
            # accounting identity is the claim here, not the rate
            problems += check_books(cell, run, 1.0)
            # the in-flight trigger dump (the FIRST fault_injected, which
            # can land before any span closed) proves the trigger class;
            # the ring keeps filling afterwards, so the postmortem's
            # request-reconstruction claims run against a final forced
            # dump of the full ring
            final_path = fleet3.flight.dump("manual", force=True)
            entries = sorted(os.listdir(flight_dir)) \
                if os.path.isdir(flight_dir) else []
            dumps = [e for e in entries if e.endswith(".jsonl")]
            if any(e.endswith(".tmp") for e in entries):
                problems.append("a .tmp sibling survived — the dump "
                                "publish is not atomic")
            header: dict = {}
            trigger = [e for e in dumps
                       if e != os.path.basename(final_path)]
            if not trigger:
                problems.append("no flight dump published (fault-site "
                                "trigger never tripped?)")
            else:
                path = os.path.join(flight_dir, trigger[0])
                with open(path, encoding="utf-8") as f:
                    raw_lines = [ln for ln in f.read().splitlines() if ln]
                try:
                    parsed_lines = [json.loads(ln) for ln in raw_lines]
                except json.JSONDecodeError as e:
                    parsed_lines = []
                    problems.append(f"dump line unparseable: {e!r}")
                if parsed_lines:
                    header = parsed_lines[0]
                    if header.get("kind") != "flight_header":
                        problems.append("dump does not lead with the "
                                        "flight_header line")
                    if header.get("reason") != "fault_site":
                        problems.append(f"dump reason "
                                        f"{header.get('reason')!r}, want "
                                        f"fault_site")
            hdr, records = postmortem.load_dump(final_path)
            report = postmortem.build_report(hdr, records)
            if report != postmortem.build_report(
                    *postmortem.load_dump(final_path)):
                problems.append("postmortem is not byte-deterministic")
            if f"shard map: v{map_version}" not in report:
                problems.append("postmortem lost the final shard-map "
                                "generation")
            if lineages and not any(x in report for x in lineages):
                problems.append("postmortem lost the model lineage")
            rids = [r["record"]["request_id"] for r in records
                    if r.get("kind") == "span"
                    and "request_id" in (r.get("record") or {})]
            if not rids:
                problems.append("no request-id spans retained in "
                                "the black box")
            missing = [rid for rid in rids[-5:]
                       if f"request_id={rid}" not in report]
            if missing:
                problems.append(f"postmortem lost admitted "
                                f"request(s) {missing}")
            cell.update(retained=len(records), request_ids=len(rids),
                        dumps=len(dumps),
                        reason=header.get("reason"), ok=not problems)
            cells.append(cell)
            print(f"[chaos-serving] fleet flight-dump: "
                  f"dumps={len(dumps)} reason={header.get('reason')} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("fleet flight-dump: "
                                + "; ".join(problems))
        finally:
            fleet3.stop()
            set_default_policy(prev_policy)

        artifact = {"budget": args.budget, "fleet": True,
                    "cells": cells, "failures": failures}
        out_path = args.output or os.path.join(tmp, "chaos_serving.json")
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=2)

    n_ok = sum(1 for c in cells if c["ok"])
    print(f"[chaos-serving] {n_ok}/{len(cells)} fleet cells passed")
    for f_ in failures:
        print(f"[chaos-serving] FAILED: {f_}")
    return 1 if failures else 0


def run_loop_chaos(args) -> int:
    """``--loop``: chaos cells for every hand-off of the closed freshness
    loop (ISSUE 17; CONTINUOUS.md "The closed loop"). A 2-shard fleet
    serves while a FeedbackAutopilot + router FleetPatchWatcher run the
    loop's legs with faults injected at each:

    - ``join-fault``: ``feedback.join`` fires → the autopilot aborts at
      the join stage; incumbent probe scores bit-identical.
    - ``launch-fault``: ``feedback.refresh_launch`` fires → aborts
      before ANY work; no staging dir survives, probes bit-identical.
    - ``publish-fault``: ``io.delta_publish`` at rate 1 (outlasting the
      retry budget) → the refresh leg fails, the loop aborts, probes
      bit-identical.
    - ``loop-activation``: a clean loop publishes per-shard patches;
      the router watcher's first epoch is faulted (``serving.reload``)
      → fleet-wide abort with the incumbent serving and probes
      bit-identical; a corrected republish (content re-key) then
      activates — versions advance everywhere, the untouched shard
      compiles NOTHING, and the loop's retry accounting is clean.

    Every labeled request targets users OWNED BY SHARD 0, so shard 1's
    patch carries no entity rows — the zero-recompile assertion.
    """
    from photon_ml_tpu.cli import serve_fleet
    from photon_ml_tpu.events import GLOBAL_BUS
    from photon_ml_tpu.feedback import AutopilotConfig, FeedbackAutopilot
    from photon_ml_tpu.fleet.sharding import shard_of_id
    from photon_ml_tpu.fleet.watcher import FleetPatchWatcher
    from photon_ml_tpu.resilience import FaultPlan, injected
    from photon_ml_tpu.resilience.retry import (
        get_default_policy,
        set_default_policy,
    )
    from photon_ml_tpu.serving import RequestLog

    cells: list[dict] = []
    failures: list[str] = []
    prev_policy = get_default_policy()
    with tempfile.TemporaryDirectory() as tmp:
        model_dir, train_path = train_model(tmp, args.rows)
        set_default_policy(prev_policy)
        fleet = serve_fleet.build_fleet([
            "--model-dir", model_dir,
            "--feature-shards", chaos_sweep.SHARDS,
            "--port", "0", "--fleet-shards", "2",
            "--microbatch", "8", "--max-wait-ms", "1",
            "--max-queue", str(args.max_queue),
        ])
        base = fleet.url
        bench_serving.wait_ready(base)
        from photon_ml_tpu.io.avro import iter_avro_file

        pool = list(iter_avro_file(train_path))[:256]

        def user_of(rec):
            return (rec.get("metadataMap") or {}).get("userId", "u0")

        touched_pool = [r for r in pool if shard_of_id(user_of(r), 2) == 0]
        probe = {"records": pool[:5]}
        probe_scores = bench_serving._http_json(
            base + "/score", probe)["scores"]

        publish_dir = os.path.join(tmp, "publish")
        reqlog_dir = os.path.join(tmp, "reqlog")
        rl = RequestLog(reqlog_dir, sample_rate=1.0, segment_records=16)
        try:
            for i in range(0, min(len(touched_pool), 64), 8):
                chunk = touched_pool[i:i + 8]
                rl.log(request_id=f"loop-{i:03d}",
                       records=[{"features": r["features"],
                                 "metadataMap": r["metadataMap"],
                                 "offset": r.get("offset"),
                                 "label": float(r["response"])}
                                for r in chunk],
                       scores=[0.0] * len(chunk), version=1, lineage=None)
        finally:
            rl.close()  # durable segments before any join reads

        config = AutopilotConfig(
            prior_dir=model_dir, publish_dir=publish_dir,
            feature_shards=chaos_sweep.SHARDS,
            coordinates=tuple(chaos_sweep.COORDS),
            update_sequence="global,perUser",
            grid=("global=0.1", "perUser=1"),
            evaluators="", data_validation="VALIDATE_DISABLED",
            fleet_shards=2, min_rows=1,
            debounce_s=0.0, min_interval_s=0.0)
        autopilot = FeedbackAutopilot(GLOBAL_BUS, config,
                                      reqlog_dirs=[reqlog_dir]).start()
        watcher = FleetPatchWatcher(fleet.router, publish_dir,
                                    poll_s=3600.0)  # driven by hand

        def drive_loop(timeout_s=180.0):
            """Post one drift event; wait for the launched loop to
            finish. Returns (refreshes_delta, aborts_delta)."""
            before = autopilot.stats()
            GLOBAL_BUS.post("quality_drift_detected", version=1,
                            kind="psi", coordinate="perUser", drift=1.0,
                            threshold=0.25, rows=999)
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                now = autopilot.stats()
                if (not now["busy"]
                        and now["refreshes"] + now["aborts"]
                        > before["refreshes"] + before["aborts"]):
                    return (now["refreshes"] - before["refreshes"],
                            now["aborts"] - before["aborts"])
                time.sleep(0.05)
            return (0, 0)

        def check_probes(problems):
            after = bench_serving._http_json(base + "/score", probe)
            if after["scores"] != probe_scores:
                problems.append("probe scores changed — the incumbent "
                                "did not keep serving bit-identically")

        def abort_cell(name, plan_obj, stage):
            cell = {"cell": name, "plan": plan_obj}
            plan = FaultPlan.from_json(plan_obj)
            with injected(plan):
                refreshed, aborted = drive_loop()
            problems = []
            if not plan.fired(plan_obj["specs"][0]["site"]):
                problems.append(
                    f"{plan_obj['specs'][0]['site']} never fired")
            if (refreshed, aborted) != (0, 1):
                problems.append(f"want 1 abort, 0 refreshes; got "
                                f"{aborted} aborts, {refreshed} refreshes")
            if os.path.exists(publish_dir) and any(
                    not e.startswith(".")
                    for e in os.listdir(publish_dir)):
                problems.append("an aborted loop left a published entry")
            check_probes(problems)
            cell.update(stage=stage, ok=not problems)
            cells.append(cell)
            print(f"[chaos-serving] loop {name}: aborted={aborted} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append(f"loop {name}: " + "; ".join(problems))

        try:
            # --- cells 1-3: each learn-leg hand-off faulted -------------
            abort_cell("join-fault", {"seed": 0, "specs": [
                {"site": "feedback.join", "rate": 1.0}]}, "join")
            abort_cell("launch-fault", {"seed": 0, "specs": [
                {"site": "feedback.refresh_launch", "rate": 1.0}]},
                "launch")
            # rate 1 with no max_fires outlasts the publish retry budget,
            # so the refresh leg itself fails and the loop aborts
            abort_cell("publish-fault", {"seed": 0, "specs": [
                {"site": "io.delta_publish", "rate": 1.0}]}, "refresh")

            # --- cell 4: clean loop, faulted activation, then retry -----
            cell = {"cell": "loop-activation"}
            problems = []
            refreshed, aborted = drive_loop()
            if (refreshed, aborted) != (1, 0):
                problems.append(f"clean loop: want 1 refresh, got "
                                f"{refreshed} refreshes {aborted} aborts")
            entries = [e for e in os.listdir(publish_dir)
                       if not e.startswith(".")] \
                if os.path.exists(publish_dir) else []
            if len(entries) != 1:
                problems.append(f"want 1 published entry, got {entries}")
            versions0 = [bench_serving._http_json(u + "/healthz")["version"]
                         for u in fleet.host_urls()]
            reload_plan = {"seed": 0,
                           "specs": [{"site": "serving.reload", "at": [0]}]}
            with injected(FaultPlan.from_json(reload_plan)):
                watcher.scan_once()
            versions1 = [bench_serving._http_json(u + "/healthz")["version"]
                         for u in fleet.host_urls()]
            if watcher.n_rejected != 1 or watcher.n_applied != 0:
                problems.append(
                    f"faulted epoch: want 1 rejected 0 applied, got "
                    f"{watcher.n_rejected}/{watcher.n_applied}")
            if versions1 != versions0:
                problems.append(f"versions moved {versions0} → "
                                f"{versions1} across an aborted epoch")
            check_probes(problems)
            if entries:
                # corrected republish in place: touching the entry's
                # content re-keys it (candidate_content_key) and the next
                # poll re-attempts — no rename dance required
                entry = os.path.join(publish_dir, entries[0])
                meta = os.path.join(entry, "patch-shard-0",
                                    "model-metadata.json")
                os.utime(meta, None)
                compiles0 = [
                    bench_serving._http_json(u + "/healthz")["compiles"]
                    for u in fleet.host_urls()]
                watcher.scan_once()
                if watcher.n_applied != 1:
                    problems.append(f"republished entry did not activate "
                                    f"(applied={watcher.n_applied})")
                versions2 = [
                    bench_serving._http_json(u + "/healthz")["version"]
                    for u in fleet.host_urls()]
                if not all(v2 > v1 for v1, v2
                           in zip(versions1, versions2)):
                    problems.append(f"versions did not advance fleet-wide"
                                    f": {versions1} → {versions2}")
                compiles1 = [
                    bench_serving._http_json(u + "/healthz")["compiles"]
                    for u in fleet.host_urls()]
                # every labeled row targeted shard-0 users, so shard 1's
                # patch has no entity rows: activation compiles nothing
                if compiles1[1] != compiles0[1]:
                    problems.append(
                        f"untouched shard recompiled: "
                        f"{compiles0[1]} → {compiles1[1]}")
                cell.update(versions=versions2,
                            untouched_compiles=compiles1[1]
                            - compiles0[1])
            cell["ok"] = not problems
            cells.append(cell)
            print(f"[chaos-serving] loop loop-activation: "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("loop loop-activation: "
                                + "; ".join(problems))
        finally:
            autopilot.stop()
            fleet.stop()
            set_default_policy(prev_policy)  # refresh runs install their own
        artifact = {"budget": args.budget, "loop": True,
                    "cells": cells, "failures": failures}
        out_path = args.output or os.path.join(tmp, "chaos_serving.json")
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
    print(f"[chaos-serving] loop cells: {len(cells)}, "
          f"failures: {len(failures)}", flush=True)
    for f_ in failures:
        print(f"[chaos-serving] FAIL {f_}", flush=True)
    return 1 if failures else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="serving chaos harness: open-loop load under seeded "
                    "fault plans, accounting + bit-parity asserts")
    p.add_argument("--seeds", default="0,1",
                   help="comma-separated plan seeds")
    p.add_argument("--rates", default="0.02,0.05",
                   help="comma-separated per-site fault rates")
    p.add_argument("--budget", choices=["smoke", "full"], default="full",
                   help="smoke = 1 seed x 1 rate, small load (tier-1)")
    p.add_argument("--requests", type=int, default=300,
                   help="offered requests per load cell")
    p.add_argument("--target-qps", type=float, default=300.0)
    p.add_argument("--error-ceiling", type=float, default=0.25,
                   help="max tolerated errored/offered fraction per cell "
                        "(injected execute faults fail whole microbatches, "
                        "so the ceiling sits well above the raw rate)")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission bound of the harness server")
    p.add_argument("--rows", type=int, default=400,
                   help="training rows for the tiny model")
    p.add_argument("--output", default=None,
                   help="where to write chaos_serving.json (default: the "
                        "harness temp dir, i.e. discarded)")
    p.add_argument("--fleet", action="store_true",
                   help="run the FLEET cells instead: an N=2 "
                        "entity-sharded fleet behind the router under "
                        "injected fleet.fanout faults, a mid-load host "
                        "kill + restart, a faulted two-phase reload, a "
                        "hot-shard storm (cold shard unharmed), a live "
                        "reshard under traffic (O(moved) repack, no "
                        "mixed-map response), a replica kill on an "
                        "R=2 fleet (zero client-visible errors), and a "
                        "flight-recorder cell (host killed mid-load "
                        "must leave a complete atomic black-box dump "
                        "whose postmortem reconstructs the final "
                        "epoch + request ids) — accounting identity "
                        "per kind, probe scores bit-identical "
                        "fleet-wide")
    p.add_argument("--loop", action="store_true",
                   help="run the FRESHNESS-LOOP cells instead: a 2-shard "
                        "fleet with a FeedbackAutopilot + router "
                        "FleetPatchWatcher; faults at feedback.join, "
                        "feedback.refresh_launch, io.delta_publish, and "
                        "the activation epoch (serving.reload) — every "
                        "hand-off aborts cleanly with the incumbent "
                        "serving bit-identically, a corrected republish "
                        "retries, and the untouched shard activates with "
                        "zero recompiles")
    args = p.parse_args(argv)

    if args.loop:
        return run_loop_chaos(args)
    if args.fleet:
        return run_fleet_chaos(args)

    seeds = [int(s) for s in args.seeds.split(",") if s]
    rates = [float(r) for r in args.rates.split(",") if r]
    requests = args.requests
    if args.budget == "smoke":
        seeds, rates, requests = seeds[:1], rates[:1], min(requests, 150)

    from photon_ml_tpu.cli import serve_game
    from photon_ml_tpu.resilience import FaultPlan, injected
    from photon_ml_tpu.resilience.retry import (
        get_default_policy,
        set_default_policy,
    )

    cells: list[dict] = []
    failures: list[str] = []
    prev_policy = get_default_policy()
    with tempfile.TemporaryDirectory() as tmp:
        model_dir, train_path = train_model(tmp, args.rows)
        set_default_policy(prev_policy)  # the training driver installs its own
        server = serve_game.build_server([
            "--model-dir", model_dir,
            "--feature-shards", chaos_sweep.SHARDS,
            "--port", "0",
            "--microbatch", "8", "--max-wait-ms", "1",
            "--max-queue", str(args.max_queue),
            # the ranked path shares the chaos sites: mixed plans must
            # fail rank batches without killing the worker, too
            "--rank-item-coordinate", "perUser", "--rank-max-k", "16",
            # brownout has its own tier-1 tests; a live controller would
            # make a cell's shed counts depend on tick timing
            "--brownout-poll-s", "0",
        ]).start()
        base = server.url
        bench_serving.wait_ready(base)
        from photon_ml_tpu.io.avro import iter_avro_file

        pool = list(iter_avro_file(train_path))[:256]
        users = list(dict.fromkeys(
            (rec.get("metadataMap") or {}).get("userId", "u0")
            for rec in pool))
        probe = {"records": pool[:5]}
        probe_scores = bench_serving._http_json(
            base + "/score", probe)["scores"]
        # ranked probe pinned alongside the scored one: delivery and
        # execute faults must never move retrieval either
        probe_rank_url = bench_serving.rank_url(base, users[0], 5)
        probe_rank = bench_serving._http_json(probe_rank_url)
        probe_topk = (probe_rank["ids"], probe_rank["scores"])
        print(f"[chaos-serving] model up at {base}, probe scores pinned "
              f"({len(probe_scores)} records, top-{len(probe_topk[0])} "
              f"rank)", flush=True)

        try:
            for seed in seeds:
                for rate in rates:
                    plan_obj = build_plan(seed, rate)
                    cell = {"seed": seed, "rate": rate, "plan": plan_obj}
                    shed0 = scraped_shed_total(base)
                    with injected(FaultPlan.from_json(plan_obj)):
                        # mixed plan: every 4th request is a GET /rank —
                        # injected execute faults land on score AND rank
                        # microbatches
                        run = bench_serving.mixed_open_loop_run(
                            base, pool, users, [1],
                            target_qps=args.target_qps,
                            requests=requests, ks=(3, 8), rank_every=4)
                    kinds = {k: run[k] for k in ("score", "rank")}
                    served = sum(len(b["corrected_ms"]) + b["reconnected"]
                                 for b in kinds.values())
                    shed = sum(b["shed"] for b in kinds.values())
                    errored = sum(len(b["errors"]) for b in kinds.values())
                    ready = settle(server, base)
                    shed_delta = scraped_shed_total(base) - shed0
                    probe_after = bench_serving._http_json(
                        base + "/score", probe)["scores"]
                    rank_after = bench_serving._http_json(probe_rank_url)
                    cell.update(
                        offered=run["offered"], served=served, shed=shed,
                        errored=errored, error_rate=errored / run["offered"],
                        per_kind={k: {"offered": b["offered"],
                                      "served": len(b["corrected_ms"]),
                                      "shed": b["shed"],
                                      "errored": len(b["errors"])}
                                  for k, b in kinds.items()},
                        shed_metric_delta=shed_delta,
                        queue_depth_after=ready["queue_depth"],
                        ready_after=ready["ready"])
                    problems = []
                    for kind, b in kinds.items():
                        if (len(b["corrected_ms"]) + b["reconnected"]
                                + b["shed"]
                                + len(b["errors"]) != b["offered"]):
                            problems.append(
                                f"{kind} accounting broke: "
                                f"{len(b['corrected_ms'])}+"
                                f"{b['reconnected']}+{b['shed']}+"
                                f"{len(b['errors'])} != {b['offered']}")
                    if shed_delta != shed:
                        problems.append(
                            f"photon_shed_total moved {shed_delta}, client "
                            f"saw {shed} 429s")
                    if errored > args.error_ceiling * run["offered"]:
                        problems.append(
                            f"error rate {errored / run['offered']:.3f} > "
                            f"ceiling {args.error_ceiling}")
                    if not ready["ready"] or ready["queue_depth"] != 0:
                        problems.append(
                            f"stranded work after drain: readyz={ready}")
                    for name, batcher in (
                            ("batcher", server.service.batcher),
                            ("rank batcher", server.service.rank_batcher)):
                        if batcher is not None and batcher.dead is not None:
                            problems.append(
                                f"{name} worker died: {batcher.dead!r}")
                    if probe_after != probe_scores:
                        problems.append(
                            "probe scores changed under load faults")
                    if (rank_after["ids"], rank_after["scores"]) != probe_topk:
                        problems.append(
                            "probe top-k changed under load faults")
                    cell["ok"] = not problems
                    cells.append(cell)
                    print(f"[chaos-serving] seed={seed} rate={rate}: "
                          f"offered={run['offered']} served={served} "
                          f"shed={shed} errored={errored} "
                          f"(rank {kinds['rank']['offered']} offered) "
                          f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
                    if problems:
                        failures.append(
                            f"seed={seed} rate={rate}: "
                            + "; ".join(problems)
                            + f" — repro with PHOTON_FAULT_PLAN="
                              f"'{json.dumps(plan_obj)}'")

            # --- incumbent-keeps-serving across an injected reload fault
            reload_plan = {"seed": 0,
                           "specs": [{"site": "serving.reload", "at": [0]}]}
            cell = {"cell": "reload-fault", "plan": reload_plan}
            version0 = bench_serving._http_json(base + "/healthz")["version"]
            reload_status = None
            with injected(FaultPlan.from_json(reload_plan)):
                try:
                    bench_serving._http_json(base + "/reload", {})
                    reload_status = 200
                except Exception as e:  # urllib HTTPError carries .code
                    reload_status = getattr(e, "code", None)
            probe_after = bench_serving._http_json(
                base + "/score", probe)["scores"]
            version1 = bench_serving._http_json(base + "/healthz")["version"]
            problems = []
            if reload_status != 409:
                problems.append(f"faulted /reload returned "
                                f"{reload_status}, want 409")
            if version1 != version0:
                problems.append(f"active version moved {version0} → "
                                f"{version1} across a faulted reload")
            if probe_after != probe_scores:
                problems.append("incumbent scores NOT bit-identical "
                                "across the faulted reload")
            cell.update(reload_status=reload_status, version=version1,
                        ok=not problems)
            cells.append(cell)
            print(f"[chaos-serving] reload-fault: status={reload_status} "
                  f"version={version1} "
                  f"{'ok' if cell['ok'] else 'FAIL'}", flush=True)
            if problems:
                failures.append("reload-fault: " + "; ".join(problems))
        finally:
            server.stop()
            server.telemetry.close()
            set_default_policy(prev_policy)

        artifact = {"budget": args.budget,
                    "error_ceiling": args.error_ceiling,
                    "cells": cells, "failures": failures}
        out_path = args.output or os.path.join(tmp, "chaos_serving.json")
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=2)

    n_ok = sum(1 for c in cells if c["ok"])
    print(f"[chaos-serving] {n_ok}/{len(cells)} cells passed")
    for f_ in failures:
        print(f"[chaos-serving] FAILED: {f_}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
