"""On-chip dense vs chunked-sparse fixed-effect layout crossover probe.

Measures one jitted ``value_and_grad`` iteration of the logistic GLM
objective for the SAME synthetic problem in both layouts across a
(dim, nnz-per-row) grid, prints the table, and reports the measured
crossover: the largest dense dim (per nnz/row) at which the dense-padded
design still beats :class:`~photon_ml_tpu.ops.design.ChunkedSparseDesign`.

The result feeds ``photon_ml_tpu/game/data.py::choose_dense_design``
(the automatic layout pick — VERDICT r2 item 4, SURVEY.md §7 hard-part #2);
the measured table lives in that function's docstring. Re-run this script
after any toolchain bump:

    PYTHONPATH=/root/repo:/root/.axon_site python tools/layout_crossover.py

Expected model: the dense iteration streams ``n*d*4`` bytes at the HBM
ceiling (~340 GB/s practical on this box), the sparse one pays XLA's
random-gather cost (~7 ns/element) on ``n*k`` entries plus chunk overhead,
so dense wins roughly while ``d <= (gather_ns * HBM_GBps / 4) * k`` ≈
``600 * k`` — the probe verifies the constant empirically.
"""

import time

import numpy as np


def bench_layouts(n, d, k, reps=8, seed=0):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops.design import ChunkedSparseDesign, DenseDesign
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMData, GLMObjective

    rng = np.random.default_rng(seed)
    rows = np.repeat(np.arange(n, dtype=np.int64), k)
    cols = rng.integers(0, d, size=n * k).astype(np.int32)
    vals = (rng.normal(size=n * k) / np.sqrt(k)).astype(np.float32)
    labels = (rng.uniform(size=n) < 0.5).astype(np.float32)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    obj = GLMObjective(LogisticLoss)

    def problem(design):
        return GLMData(design=design, labels=jnp.asarray(labels),
                       offsets=jnp.zeros(n, jnp.float32),
                       weights=jnp.ones(n, jnp.float32))

    step = jax.jit(lambda w, data: obj.value_and_grad(w, data, 1e-3))

    def run(design):
        # NOTE sync: on the axon PJRT platform block_until_ready does not
        # block; the reliable barrier is a D2H transfer (bench.py note).
        # Iterations are CHAINED (w updated from the grad) so each rep is a
        # genuine data-dependent execution — like real solver iterations —
        # and the final float() waits for the whole chain.
        data = problem(design)
        wi = w
        v, g = step(wi, data)
        _ = float(v)  # compile + warm barrier
        t0 = time.perf_counter()
        for _ in range(reps):
            v, g = step(wi, data)
            wi = wi - 1e-4 * g
        _ = float(v)
        return (time.perf_counter() - t0) / reps

    # min of two independent passes per layout: the first timed pass after
    # a fresh compile measured ~10x slow on this tunnel (cold-path effect);
    # the repeat converges to the steady state
    dense_bytes = n * d * 4
    t_dense = None
    if dense_bytes <= 4 << 30:  # don't OOM the probe itself
        x = np.zeros((n, d), np.float32)
        x[rows, cols.astype(np.int64)] = vals
        design = DenseDesign(x=jnp.asarray(x))
        t_dense = min(run(design), run(design))
        del x, design
    sp = ChunkedSparseDesign.from_coo(
        rows.astype(np.int32), cols, vals, n_rows=n, n_cols=d)
    t_sparse = min(run(sp), run(sp))
    return t_dense, t_sparse


def main():
    import jax

    # ~30 s/shape through the remote-compile tunnel without it (bench.py
    # compile-budget note); 18 (d, k) points x 2 layouts in this grid
    import os
    import tempfile

    cache = os.path.join(tempfile.gettempdir(), "photon-xla-cache")
    os.makedirs(cache, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    print(f"devices: {jax.devices()}")
    print(f"{'d':>7} {'k':>4} {'n':>8} {'dense_ms':>9} {'sparse_ms':>10} "
          f"{'winner':>7} {'ratio':>6}")
    results = []
    for d in (512, 2048, 4096, 8192, 16384, 65536):
        for k in (8, 32, 128):
            if k >= d:
                continue
            n = int(max(20_000, min(400_000, 1_000_000_000 // (4 * d))))
            t_dense, t_sparse = bench_layouts(n, d, k)
            if t_dense is None:
                print(f"{d:>7} {k:>4} {n:>8} {'skip':>9} "
                      f"{t_sparse*1e3:>10.2f} {'sparse':>7} {'':>6}")
                continue
            win = "dense" if t_dense <= t_sparse else "sparse"
            ratio = t_sparse / t_dense
            results.append((d, k, win))
            print(f"{d:>7} {k:>4} {n:>8} {t_dense*1e3:>9.2f} "
                  f"{t_sparse*1e3:>10.2f} {win:>7} {ratio:>6.2f}")
    # report measured crossover constant: max d/k where dense still wins
    cs = [d / k for d, k, win in results if win == "dense"]
    if cs:
        print(f"\nmax d/k with dense winning: {max(cs):.0f}")


if __name__ == "__main__":
    main()
