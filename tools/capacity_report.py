#!/usr/bin/env python
"""Capacity report: bottleneck attribution from saved fleet artifacts.

Where ``fleet_report.py`` answers "which shard is hot", this tool
answers the capacity planner's questions — WHAT resource binds first,
how much sustainable throughput is left before it saturates, and which
shard hits its wall soonest — from artifacts the observability plane
already saves:

- ``history.json`` — a saved ``GET /history`` body (host or router
  tier). Every retained tick carries the USE-method series the
  saturation sampler derived (``resource_util``, ``duty_cycle``,
  ``open_connections`` — telemetry/saturation.py), so the report's
  per-window binding resource is read straight off the ring;
- ``metrics.aggregate.prom`` (or ``metrics.prom``) — a saved fleet
  ``GET /metrics`` fold (optional: the per-shard capacity table needs
  the fold's fanned-out host-owned gauges; a host-tier snapshot renders
  without shard attribution).

The **binding resource** of a window is the argmax of that tick's
per-resource utilization (ties break to the lexicographically-first
resource — deterministic, like every vocabulary in this codebase). The
**max-sustainable-QPS projection** scales the observed rate by the
binding resource's remaining headroom: at utilization ``u`` with
observed rate ``q``, the linear projection is ``q / u`` — a first-order
estimate (real systems curve near saturation), which is why the report
prints it against the ``--slo-objective-ms`` evidence: a window whose
p99 already exceeds the objective has NO headroom regardless of the
utilization arithmetic.

The report is a pure function of its inputs (no clocks, no environment
reads) — the golden test feeds fixture artifacts and compares bytes.

Usage::

    python tools/capacity_report.py DIR [--slo-objective-ms MS]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Mapping, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.telemetry import prometheus as tprom  # noqa: E402

#: timeline windows rendered — the ring holds more; the page shows the
#: recent trend (matches fleet_report's tail length)
WINDOW_TAIL = 12


def binding_of(resource_util: Mapping) -> "tuple[str, float]":
    """(resource, utilization) with the highest utilization; ties break
    to the lexicographically-first resource name. ``("(none)", 0.0)``
    when the tick carries no utilization evidence."""
    best: Optional[tuple[str, float]] = None
    for resource in sorted(resource_util):
        value = float(resource_util[resource])
        if best is None or value > best[1]:
            best = (str(resource), value)
    return best if best is not None else ("(none)", 0.0)


def window_rows(history: Mapping) -> list[dict]:
    """One row per retained tick: observed rate (requests over the
    inter-tick wall time), duty cycle, open connections, p99, and the
    binding resource. The first tick has no predecessor, so its rate is
    None (rendered ``-``)."""
    rows: list[dict] = []
    prev_ts: Optional[float] = None
    for snap in history.get("snapshots", ()):
        series = snap.get("series") or {}
        ts = snap.get("ts")
        qps: Optional[float] = None
        requests = series.get("requests")
        if (requests is not None and prev_ts is not None
                and ts is not None and ts > prev_ts):
            qps = float(requests) / (float(ts) - float(prev_ts))
        binding, util = binding_of(series.get("resource_util") or {})
        rows.append({
            "tick": snap.get("tick"),
            "qps": qps,
            "requests": requests,
            "duty_cycle": series.get("duty_cycle"),
            "open_connections": series.get("open_connections"),
            "p99_s": series.get("latency_p99"),
            "binding": binding,
            "binding_util": util,
        })
        prev_ts = float(ts) if ts is not None else prev_ts
    return rows


def projection(rows: Sequence[Mapping],
               slo_objective_ms: float) -> Optional[dict]:
    """Max-sustainable-QPS estimate from the window with the most
    saturation evidence: the FIRST row with the highest binding
    utilization and an observed rate. None when no window carries both
    a rate and non-zero utilization."""
    peak: Optional[Mapping] = None
    for row in rows:
        if row["qps"] is None or row["binding_util"] <= 0.0:
            continue
        if peak is None or row["binding_util"] > peak["binding_util"]:
            peak = row
    if peak is None:
        return None
    max_qps = peak["qps"] / peak["binding_util"]
    p99_ms = (None if peak["p99_s"] is None
              else float(peak["p99_s"]) * 1e3)
    slo_ok = (None if (p99_ms is None or slo_objective_ms <= 0)
              else p99_ms <= slo_objective_ms)
    return {"tick": peak["tick"], "qps": peak["qps"],
            "binding": peak["binding"],
            "binding_util": peak["binding_util"],
            "max_qps": max_qps, "headroom_qps": max_qps - peak["qps"],
            "p99_ms": p99_ms, "slo_ok": slo_ok}


def shard_capacity(parsed: Mapping) -> list[dict]:
    """Per-shard binding resource from a FOLDED snapshot, where the
    host-owned ``photon_resource_utilization`` gauges carry both
    ``shard`` and ``resource`` labels (tools/metrics_fold.py /
    fleet/observe.py). Empty on a host-tier snapshot."""
    best: dict[str, tuple[str, float]] = {}
    opens: dict[str, float] = {}
    for labels, value in parsed.get("photon_resource_utilization", ()):
        shard, resource = labels.get("shard"), labels.get("resource")
        if shard is None or resource is None:
            continue
        value = float(value)
        cur = best.get(shard)
        if (cur is None or value > cur[1]
                or (value == cur[1] and resource < cur[0])):
            best[shard] = (str(resource), value)
    for labels, value in parsed.get("photon_connections_open", ()):
        shard = labels.get("shard")
        if shard is not None:
            opens[shard] = opens.get(shard, 0.0) + float(value)
    return [{"shard": s, "binding": best[s][0], "util": best[s][1],
             "open_connections": opens.get(s, 0.0)}
            for s in sorted(best, key=lambda k: (len(k), k))]


def build_report(history: Mapping, prom_text: str = "",
                 slo_objective_ms: float = 0.0) -> str:
    """The report text (the CLI prints it; tests golden-compare it)."""
    lines: list[str] = ["== photon capacity report =="]
    rows = window_rows(history)
    bits = [f"{len(rows)} retained tick(s)",
            f"source {history.get('source')}"]
    if slo_objective_ms > 0:
        bits.append(f"SLO objective {slo_objective_ms:g}ms")
    lines.append("; ".join(bits))

    # --- per-window binding ------------------------------------------------
    lines.append("")
    lines.append(f"-- binding resource per window (last "
                 f"{min(len(rows), WINDOW_TAIL)} of {len(rows)}) --")
    lines.append(f"{'tick':<6} {'qps':>8} {'duty':>6} {'conns':>6} "
                 f"{'p99_ms':>8} {'binding':<18} {'util':>6}")
    for row in rows[-WINDOW_TAIL:] or ():
        qps = "-" if row["qps"] is None else f"{row['qps']:.4g}"
        duty = ("-" if row["duty_cycle"] is None
                else f"{row['duty_cycle']:.3f}")
        conns = ("-" if row["open_connections"] is None
                 else f"{int(row['open_connections'])}")
        p99 = ("-" if row["p99_s"] is None
               else f"{row['p99_s'] * 1e3:.3f}")
        lines.append(
            f"t{row['tick']:<5} {qps:>8} {duty:>6} {conns:>6} "
            f"{p99:>8} {row['binding']:<18} "
            f"{row['binding_util']:>6.3f}")
    if not rows:
        lines.append("(no snapshots retained)")

    # --- projection --------------------------------------------------------
    proj = projection(rows, slo_objective_ms)
    lines.append("")
    lines.append("-- max-sustainable-QPS projection --")
    if proj is None:
        lines.append("no saturation evidence (no window carries both an "
                     "observed rate and non-zero utilization)")
    else:
        lines.append(
            f"peak evidence at t{proj['tick']}: {proj['qps']:.4g} qps "
            f"with {proj['binding']} at "
            f"{proj['binding_util'] * 100:.1f}% utilization")
        lines.append(
            f"linear projection: ~{proj['max_qps']:.4g} qps sustainable "
            f"(headroom ~{proj['headroom_qps']:.4g} qps) before "
            f"{proj['binding']} saturates")
        if proj["slo_ok"] is False:
            lines.append(
                f"WARNING: p99 {proj['p99_ms']:.3f}ms already exceeds "
                f"the {slo_objective_ms:g}ms objective at the peak "
                f"window — headroom is 0 regardless of utilization")
        elif proj["slo_ok"] is True:
            lines.append(
                f"p99 {proj['p99_ms']:.3f}ms within the "
                f"{slo_objective_ms:g}ms objective at the peak window")

    # --- per-shard capacity ------------------------------------------------
    if prom_text:
        shards = shard_capacity(tprom.parse_text(prom_text))
        if shards:
            lines.append("")
            lines.append("-- per-shard capacity (folded snapshot) --")
            lines.append(f"{'shard':<6} {'binding':<18} {'util':>6} "
                         f"{'conns':>6}")
            for row in shards:
                lines.append(
                    f"{row['shard']:<6} {row['binding']:<18} "
                    f"{row['util']:>6.3f} "
                    f"{int(row['open_connections']):>6d}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Render a capacity/bottleneck report from saved "
                    "observability artifacts (history ring + metrics "
                    "fold)")
    p.add_argument("run_dir", help="directory holding the saved "
                                   "artifacts")
    p.add_argument("--slo-objective-ms", type=float, default=0.0,
                   help="latency objective the projection is judged "
                        "against (same value as serve_fleet "
                        "--slo-objective-ms); 0 = skip the check")
    args = p.parse_args(argv)
    history_path = os.path.join(args.run_dir, "history.json")
    if not os.path.exists(history_path):
        print(f"no history.json under {args.run_dir} (save the server's "
              f"GET /history body — the capacity plane's evidence lives "
              f"in the retained ring)", file=sys.stderr)
        return 1
    with open(history_path, encoding="utf-8") as f:
        history = json.load(f)
    prom_text = ""
    for name in ("metrics.aggregate.prom", "metrics.prom"):
        prom_path = os.path.join(args.run_dir, name)
        if os.path.exists(prom_path):
            with open(prom_path, encoding="utf-8") as f:
                prom_text = f.read()
            break
    sys.stdout.write(build_report(
        history, prom_text, slo_objective_ms=args.slo_objective_ms))
    return 0


if __name__ == "__main__":
    sys.exit(main())
