"""Load generator / latency bench for the online serving subsystem.

Spins up an in-process :class:`GameServer` over a trained GAME model (or
targets an already-running server via ``--url``) and replays request
traffic in one of two modes:

- ``--mode closed`` (default, the historical mode): ``--concurrency``
  worker threads each issue the next request the moment the previous one
  returns. Percentiles are labeled ``closed_loop_*`` because this
  methodology **hides coordinated omission** — a server stall simply
  pauses the senders, so the stall shows up in at most ``concurrency``
  samples instead of every request that WOULD have arrived. Closed-loop
  numbers measure the server at the load it permits, not the load you
  asked for. (The old ``value``/``p99_ms`` keys remain as aliases so
  ``bench_gate`` baselines keep comparing.)
- ``--mode open --target-qps N``: requests fire on a fixed schedule
  (request *i* is due at ``t0 + i/N``) regardless of completions, and
  every latency is measured from the request's SCHEDULED time — the
  HdrHistogram-style correction. If the server stalls, queued schedule
  slots keep accumulating wait, so ``corrected_p99`` reflects what real
  open traffic would experience; the ``uncorrected_*`` numbers (send →
  response) are reported next to it to expose the gap.
  ``--slo-p99-ms`` adds a p99 SLO gate on the corrected percentile whose
  ``ok``/``regression`` verdict is produced by ``tools/bench_gate.py``
  (exit 1 on regression).
- ``--mode ranked --rank-item-coordinate COORD``: the ``/rank``
  workload (SERVING.md "Ranked retrieval") — a closed-loop k sweep
  (``--rank-ks``, per-k p50/p99) followed by an open-loop ranked load
  with shed classification, `photon_rank_*` metric parity for
  in-process runs, and the same optional p99 SLO gate.

Both modes also report:

- the engine recompile count across the load phase (the zero-recompile
  contract: after warmup it must not move),
- a ``/metrics`` scrape (before and after) folding the SERVER'S own
  histograms into the report: request-latency quantiles from bucket
  deltas, the per-stage request-path breakdown
  (``photon_serving_stage_seconds{stage=parse|queue_wait|batch_assemble|
  execute|respond}``), the recompile counter delta, and — for in-process
  runs, where the bench is the only traffic — parity assertions between
  the scraped counters and the client-side tallies,
- the ``photon_quality_*`` model-quality families (quality/monitor.py)
  with the cold-start parity assert.

Output: one JSON line per metric + a terminal ``suite_summary`` line, the
same artifact shape as bench.py.

Usage::

    python tools/bench_serving.py --model-dir out/ \
        --feature-shards 'global=fixed|intercept,user=user|noIntercept' \
        --requests 500 --concurrency 4
    python tools/bench_serving.py --model-dir out/ --feature-shards ... \
        --mode open --target-qps 200 --requests 1000 --slo-p99-ms 50
"""

from __future__ import annotations

import argparse
import http.client
import json
import math
import threading
import time
import urllib.error
import urllib.request

#: transient connection deaths the open-loop client retries (bounded):
#: under full-suite CPU contention the stdlib ThreadingHTTPServer's
#: accept backlog can RST a connection the server never read — the
#: request was NOT served, so one reconnect is correctness, not retry
#: amplification. A request that succeeds only after reconnecting is
#: counted (``reconnected``) and EXCLUDED from the latency percentiles:
#: its latency measures the client's retry loop, not the server.
_RESET_ERRORS = (ConnectionResetError, BrokenPipeError,
                 http.client.RemoteDisconnected)

#: bounded reconnect budget per request
_MAX_RECONNECTS = 2


def _is_reset(e: BaseException) -> bool:
    if isinstance(e, _RESET_ERRORS):
        return True
    return (isinstance(e, urllib.error.URLError)
            and not isinstance(e, urllib.error.HTTPError)
            and isinstance(getattr(e, "reason", None), _RESET_ERRORS))


def _percentile(xs, q):
    import numpy as np

    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def _http_json(url: str, payload=None, timeout=60.0):
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def wait_ready(base: str, timeout_s: float = 60.0) -> dict:
    """Block until the server reports READY on ``/readyz`` (503 = up but
    not serving — warming, no model yet, or max brownout). Falls back to
    one ``/healthz`` probe against pre-readyz builds (404)."""
    deadline = time.perf_counter() + timeout_s
    last = None
    while time.perf_counter() < deadline:
        try:
            return _http_json(base + "/readyz", timeout=5.0)
        except urllib.error.HTTPError as e:
            if e.code == 404:  # pre-readyz server: liveness is the best gate
                return _http_json(base + "/healthz", timeout=5.0)
            last = f"HTTP {e.code}"
        except Exception as e:
            last = repr(e)
        time.sleep(0.1)
    raise SystemExit(f"server at {base} never became ready within "
                     f"{timeout_s}s (last: {last})")


def _scrape_metrics(base: str):
    """Parsed /metrics snapshot, or None against a server without the
    endpoint (pre-telemetry builds)."""
    from photon_ml_tpu.telemetry.prometheus import parse_text

    try:
        with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
            return parse_text(resp.read().decode())
    except Exception:
        return None


def _scrape_process_metrics():
    """Parsed snapshot of the process-global registry. For an in-process
    fleet this is the right source for ROUTER-owned counters: the
    router's folded ``/metrics`` merges every member's text, and since
    in-process hosts share the router's registry the same series would
    be re-counted once per member."""
    from photon_ml_tpu.telemetry.prometheus import parse_text, render

    return parse_text(render())


def _counter_delta(m0, m1, name: str, **match) -> float:
    """Summed delta of a counter family between two scrapes, restricted
    to series whose labels carry every ``match`` pair."""
    def total(m):
        return sum(v for labels, v in (m or {}).get(name, [])
                   if all(labels.get(k) == want for k, want in match.items()))
    return total(m1) - total(m0)


def fleet_elastic_extras(m0, m1, offered: int) -> dict:
    """Replica-group activity over one load window, from the router's
    folded /metrics: how many legs were retried on a replica, how many
    backups were hedged (rate normalised by offered requests), and how
    many shard-map epochs activated mid-window (0 in a plain bench)."""
    hedges = int(_counter_delta(m0, m1, "photon_fleet_hedges_total"))
    return {
        "replica_retries": int(
            _counter_delta(m0, m1, "photon_fleet_replica_retries_total")),
        "hedges": hedges,
        "hedge_rate": round(hedges / offered, 4) if offered else 0.0,
        "reshard_epochs": int(
            _counter_delta(m0, m1, "photon_fleet_shardmap_epochs_total",
                           outcome="activated")),
    }


def _histogram_delta(m0, m1, name: str):
    """(uppers, cumulative-count deltas, count delta) for one label-free
    histogram between two scrapes — the load window's own distribution."""
    from photon_ml_tpu.telemetry.prometheus import series_value

    buckets1 = m1.get(name + "_bucket", [])
    uppers, deltas = [], []
    for labels, v1 in buckets1:
        le = labels.get("le")
        v0 = series_value(m0 or {}, name + "_bucket", {"le": le})
        uppers.append(math.inf if le == "+Inf" else float(le))
        deltas.append(int(v1 - v0))
    order = sorted(range(len(uppers)), key=lambda i: uppers[i])
    uppers = [uppers[i] for i in order]
    deltas = [deltas[i] for i in order]
    count = (series_value(m1, name + "_count")
             - series_value(m0 or {}, name + "_count"))
    return uppers[:-1], deltas, int(count)


def _labeled_histogram_delta(m0, m1, name: str, label_name: str):
    """Per label value: (uppers, cumulative-count deltas, count delta) of a
    one-label histogram family between two scrapes (the per-stage
    breakdown's raw material)."""
    from photon_ml_tpu.telemetry.prometheus import series_value

    by_label: dict[str, list] = {}
    for labels, v1 in m1.get(name + "_bucket", []):
        lv = labels.get(label_name)
        le = labels.get("le")
        if lv is None or le is None:
            continue
        v0 = series_value(m0 or {}, name + "_bucket",
                          {label_name: lv, "le": le})
        by_label.setdefault(lv, []).append(
            (math.inf if le == "+Inf" else float(le), int(v1 - v0)))
    out = {}
    for lv, pairs in by_label.items():
        pairs.sort(key=lambda p: p[0])
        uppers = [u for u, _ in pairs]
        deltas = [d for _, d in pairs]
        count = (series_value(m1, name + "_count", {label_name: lv})
                 - series_value(m0 or {}, name + "_count",
                                {label_name: lv}))
        out[lv] = (uppers[:-1], deltas, int(count))
    return out


def stage_breakdown(m0, m1) -> dict:
    """The request-path critical path across the load window, per stage:
    count + bucket-interpolated p50/p99 ms from the server's
    ``photon_serving_stage_seconds`` histograms."""
    from photon_ml_tpu.telemetry.metrics import quantile_from_buckets

    out = {}
    for stage, (uppers, cum, count) in sorted(_labeled_histogram_delta(
            m0, m1, "photon_serving_stage_seconds", "stage").items()):
        if count <= 0:
            continue
        out[stage] = {
            "count": count,
            "p50_ms": round(quantile_from_buckets(uppers, cum, 0.50) * 1e3, 3),
            "p99_ms": round(quantile_from_buckets(uppers, cum, 0.99) * 1e3, 3),
        }
    return out


def open_loop_run(base: str, pool, sizes, *, target_qps: float,
                  requests: int, concurrency: int = 16,
                  timeout: float = 60.0) -> dict:
    """Fire ``requests`` requests on an open-loop schedule at
    ``target_qps`` and return schedule-corrected + uncorrected latencies.

    Request *i* is DUE at ``start + i/target_qps``; a worker that reaches
    it early sleeps, one that reaches it late (every worker stuck behind a
    server stall) sends immediately — and the wait it accumulated counts
    into the corrected latency, exactly as it would for a real arrival
    process. ``concurrency`` bounds in-flight requests (stdlib urllib has
    no async client); size it above ``target_qps × expected latency`` so
    the schedule, not the sender, is the limiting factor."""
    lock = threading.Lock()
    counter = {"i": 0}
    corrected: list[float] = []
    uncorrected: list[float] = []
    errors: list[str] = []
    shed = {"n": 0}
    reconnected = {"n": 0}
    sent_rows = {"n": 0}
    start = time.perf_counter() + 0.05

    def worker():
        while True:
            with lock:
                i = counter["i"]
                if i >= requests:
                    return
                counter["i"] += 1
            due = start + i / target_qps
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            size = sizes[i % len(sizes)]
            recs = [pool[(i + j) % len(pool)] for j in range(size)]
            t_send = time.perf_counter()
            resets = 0
            while True:
                try:
                    out = _http_json(base + "/score", {"records": recs},
                                     timeout=timeout)
                    assert len(out["scores"]) == size
                    outcome = "served"
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        # shed by admission control: that's the server
                        # WORKING under overload, not failing — counted
                        # separately, excluded from the latency population
                        outcome = "shed"
                    else:
                        outcome = repr(e)
                except Exception as e:
                    if _is_reset(e) and resets < _MAX_RECONNECTS:
                        # backlog RST: the server never read the request —
                        # reconnect (bounded), count it, keep the latency
                        # out of the percentiles
                        resets += 1
                        continue
                    outcome = repr(e)
                break
            with lock:
                if outcome == "served":
                    if resets:
                        reconnected["n"] += 1
                    else:
                        t_done = time.perf_counter()
                        corrected.append((t_done - due) * 1e3)
                        uncorrected.append((t_done - t_send) * 1e3)
                    sent_rows["n"] += size
                elif outcome == "shed":
                    shed["n"] += 1
                else:
                    errors.append(outcome)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    # the load-accounting identity every run must satisfy (and the chaos
    # harness asserts): served + shed + errored == offered (served =
    # measured + reconnect-served)
    assert len(corrected) + reconnected["n"] + shed["n"] \
        + len(errors) == requests
    return {"corrected_ms": corrected, "uncorrected_ms": uncorrected,
            "errors": errors, "shed": shed["n"], "offered": requests,
            "reconnected": reconnected["n"],
            "wall_s": wall, "rows": sent_rows["n"],
            "achieved_qps": ((len(corrected) + reconnected["n"]) / wall
                             if wall > 0 else 0.0)}


def rank_url(base: str, user, k) -> str:
    import urllib.parse

    return (f"{base}/rank?user={urllib.parse.quote(str(user))}"
            f"&k={int(k)}")


def mixed_open_loop_run(base: str, pool, users, sizes, *,
                        target_qps: float, requests: int,
                        ks=(10,), rank_every: int = 0,
                        concurrency: int = 16,
                        timeout: float = 60.0) -> dict:
    """Open-loop load mixing ``POST /score`` and ``GET /rank`` on one
    fixed arrival schedule (the coordinated-omission-proof generator of
    :func:`open_loop_run`, per-kind books).

    ``rank_every=0`` sends only scores, ``1`` only ranks, ``N>1`` makes
    every Nth request a rank. Returns ``{"score": {...}, "rank": {...}}``
    with per-kind ``offered``/``corrected_ms``/``shed``/``errors``/
    ``reconnected``/``lineages``/``shard_maps``; each kind independently
    satisfies (and asserts) the accounting identity ``served + shed +
    errored == offered`` (served = measured + reconnect-served) — what
    the chaos harness checks per kind under injected faults, along with
    the ``lineages`` set staying a singleton (no mixed-lineage response)
    and ``shard_maps`` (the fleet's stamped map hashes) staying within
    the maps the load window legitimately crossed."""
    lock = threading.Lock()
    counter = {"i": 0}
    books = {kind: {"offered": 0, "corrected_ms": [], "uncorrected_ms": [],
                    "shed": 0, "errors": [], "reconnected": 0,
                    "lineages": set(), "shard_maps": set()}
             for kind in ("score", "rank")}
    start = time.perf_counter() + 0.05

    def worker():
        while True:
            with lock:
                i = counter["i"]
                if i >= requests:
                    return
                counter["i"] += 1
            due = start + i / target_qps
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            is_rank = bool(rank_every) and i % rank_every == 0
            kind = "rank" if is_rank else "score"
            with lock:
                books[kind]["offered"] += 1
            t_send = time.perf_counter()
            resets = 0
            out = None
            while True:
                try:
                    if is_rank:
                        out = _http_json(
                            rank_url(base, users[i % len(users)],
                                     ks[i % len(ks)]), timeout=timeout)
                        assert "ids" in out
                    else:
                        size = sizes[i % len(sizes)]
                        recs = [pool[(i + j) % len(pool)]
                                for j in range(size)]
                        out = _http_json(base + "/score",
                                         {"records": recs},
                                         timeout=timeout)
                        assert len(out["scores"]) == size
                    outcome = "served"
                except urllib.error.HTTPError as e:
                    outcome = "shed" if e.code == 429 \
                        else f"{kind}: {e!r}"
                except Exception as e:
                    if _is_reset(e) and resets < _MAX_RECONNECTS:
                        resets += 1
                        continue
                    outcome = f"{kind}: {e!r}"
                break
            with lock:
                if outcome == "served":
                    # every served response's content lineage: the chaos
                    # harness asserts a fleet never answered from two
                    # model generations in one load window
                    if "lineage" in out:
                        books[kind]["lineages"].add(out["lineage"])
                    if "shard_map" in out:
                        books[kind]["shard_maps"].add(out["shard_map"])
                    if resets:
                        books[kind]["reconnected"] += 1
                    else:
                        t_done = time.perf_counter()
                        books[kind]["corrected_ms"].append(
                            (t_done - due) * 1e3)
                        books[kind]["uncorrected_ms"].append(
                            (t_done - t_send) * 1e3)
                elif outcome == "shed":
                    books[kind]["shed"] += 1
                else:
                    books[kind]["errors"].append(outcome)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    for kind, b in books.items():
        assert (len(b["corrected_ms"]) + b["reconnected"] + b["shed"]
                + len(b["errors"]) == b["offered"]), (kind, b)
    books["wall_s"] = wall
    books["offered"] = requests
    return books


def slo_gate_verdict(corrected_p99_ms: float, slo_p99_ms: float,
                     shed_rate: float = 0.0) -> dict:
    """The p99 SLO as a ``tools/bench_gate.py`` verdict: headroom =
    slo/p99 (a rate-shaped metric, higher is better) gated at threshold 0
    against a fixed baseline of 1.0 — headroom < 1 (p99 over SLO) is a
    ``regression``, headroom ≥ 1 is ``ok``. Reusing the gate keeps one
    verdict vocabulary across the whole bench trajectory.

    ``shed_rate`` (shed responses / offered requests) distinguishes the
    two overload failure shapes: a regression with sheds is the server
    DEGRADING BY DESIGN (``cause="shedding"`` — raise capacity or the
    queue bound), one without is plain tail latency (``cause="slow"`` —
    optimize the path). Shed responses are excluded from the percentiles
    the gate judges."""
    import bench_gate

    headroom = (slo_p99_ms / corrected_p99_ms
                if corrected_p99_ms > 0 else float("inf"))
    current = {"metrics": {
        "serving_p99_slo_headroom": {"value": min(headroom, 1e9)}}}
    baseline = {"metrics": {
        "serving_p99_slo_headroom": {"value": 1.0}}}
    verdict = bench_gate.gate({"rc": 0, "summary": current},
                              {"rc": 0, "summary": baseline},
                              threshold=0.0)
    verdict["slo_p99_ms"] = slo_p99_ms
    verdict["corrected_p99_ms"] = round(corrected_p99_ms, 3)
    verdict["headroom"] = round(headroom, 4)
    verdict["shed_rate"] = round(shed_rate, 4)
    if verdict.get("verdict") == "regression":
        verdict["cause"] = "shedding" if shed_rate > 0 else "slow"
    return verdict


def _synthesize_pool(pool_size, shard_configs, index_maps, ids_by_type):
    """Synthetic replay records over a model's own feature space +
    per-entity-type raw-id universe (plus ~10% unseen entities — the
    cold-start path is part of traffic)."""
    import numpy as np

    from photon_ml_tpu.types import NAME_TERM_DELIMITER

    rng = np.random.default_rng(7)
    records = []
    for i in range(pool_size):
        feats = []
        for cfg in shard_configs:
            names = [k for k in index_maps[cfg.shard_id].names()
                     if not k.startswith("(INTERCEPT)")]
            take = rng.choice(len(names), size=min(6, len(names)),
                              replace=False)
            for t in take:
                name, _, term = names[int(t)].partition(NAME_TERM_DELIMITER)
                feats.append({"name": name, "term": term,
                              "value": float(rng.normal())})
        meta = {}
        for re_type, ids in ids_by_type.items():
            if ids and rng.random() > 0.1:
                meta[re_type] = ids[int(rng.integers(len(ids)))]
            else:
                meta[re_type] = f"__cold_{i}"
        records.append({"features": feats, "metadataMap": meta,
                        "offset": None})
    return records


def _request_pool(args, server):
    """Records to replay: --data avro file when given, else synthetic
    records drawn from the model's own feature/entity universe (plus a
    slice of unseen entities — the cold-start path serves too)."""
    if args.data:
        from photon_ml_tpu.io.avro import iter_avro_file

        records = list(iter_avro_file(args.data))
        if not records:
            raise SystemExit(f"--data {args.data!r} holds no records")
        return records
    if server is None:
        raise SystemExit("--data is required with --url (a remote bench "
                         "can't introspect the model's feature space)")
    sm = server.service.registry.active()
    ids_by_type = {store.random_effect_type: list(store.row_of_id)
                   for store in sm.stores.values()}
    return _synthesize_pool(args.pool, sm.engine.shard_configs,
                            sm.index_maps, ids_by_type)


def fleet_request_pool(args, fleet):
    """The fleet twin of :func:`_request_pool`: the id universe is the
    UNION of every host's shard slice, so replay traffic exercises every
    shard (plus the cold slice, which hashes wherever it hashes)."""
    if args.data:
        return _request_pool(args, None)  # returns the replay file
    ids_by_type: dict = {}
    sm0 = fleet.hosts[0].service.registry.active()
    for host in fleet.hosts:
        for store in host.service.registry.active().stores.values():
            ids_by_type.setdefault(
                store.random_effect_type, []).extend(store.row_of_id)
    return _synthesize_pool(args.pool, sm0.engine.shard_configs,
                            sm0.index_maps, ids_by_type)


def _rank_users(server, pool, n: int = 64) -> list:
    """Probe-user pool for ranked load: the non-item coordinates' raw ids
    when the server is in-process (plus a cold slice), else ids mined
    from the request pool's metadata, else synthetic cold users."""
    users = []
    if server is not None:
        sm = server.service.registry.active()
        eng = sm.rank_engine
        if eng is not None:
            for cid in eng._rank_re_order:
                users.extend(sm.stores[cid].row_of_id)
    if not users:
        for rec in pool:
            users.extend((rec.get("metadataMap") or {}).values())
    users = list(dict.fromkeys(str(u) for u in users))[:n]
    # ~1/8 cold users: the unknown-entity path ranks too
    users.extend(f"__rank_cold_{i}" for i in range(max(len(users) // 8, 1)))
    return users


def run_ranked(args, server, base: str, pool) -> None:
    """``--mode ranked``: closed-loop k sweep + open-loop ranked load
    with shed classification — the ranked twin of the score bench.
    Prints the same one-JSON-line-per-metric artifact and exits non-zero
    on errors, scrape disparity, or an SLO regression."""
    users = _rank_users(server, pool)
    ks = [int(k) for k in args.rank_ks.split(",") if k]
    health0 = _http_json(base + "/healthz")
    if "rank" not in health0:
        raise SystemExit("--mode ranked needs a rank-enabled server "
                         "(serve_game --rank-item-coordinate, or pass "
                         "--rank-item-coordinate for in-process spawn)")
    rank_compiles0 = health0["rank"]["compiles"]
    metrics0 = _scrape_metrics(base)
    results, errors = [], []

    def closed_sweep(k, n_req, conc):
        lats: list = []
        lk = threading.Lock()
        cnt = {"i": 0}

        def w():
            while True:
                with lk:
                    if cnt["i"] >= n_req:
                        return
                    i = cnt["i"]
                    cnt["i"] += 1
                t0 = time.perf_counter()
                try:
                    out = _http_json(rank_url(base, users[i % len(users)],
                                              k))
                    assert "ids" in out
                except Exception as e:
                    with lk:
                        errors.append(f"k={k}: {e!r}")
                    continue
                with lk:
                    lats.append((time.perf_counter() - t0) * 1e3)

        threads = [threading.Thread(target=w) for _ in range(conc)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return lats

    per_k = {}
    closed_all: list = []
    n_per_k = max(args.requests // max(len(ks), 1), 1)
    t0 = time.perf_counter()
    for k in ks:
        lats = closed_sweep(k, n_per_k, args.concurrency)
        closed_all.extend(lats)
        per_k[str(k)] = {"n": len(lats),
                         "p50_ms": round(_percentile(lats, 50), 3),
                         "p99_ms": round(_percentile(lats, 99), 3)}
    closed_wall = time.perf_counter() - t0
    results.append({
        "metric": "serving_ranked_latency_ms",
        "value": round(_percentile(closed_all, 50), 3),
        "unit": "ms p50 (closed-loop GET /rank, k sweep; hides "
                "coordinated omission — see the open-loop line)",
        "closed_loop_p50_ms": round(_percentile(closed_all, 50), 3),
        "closed_loop_p99_ms": round(_percentile(closed_all, 99), 3),
        "per_k": per_k,
        "requests_per_sec": round(len(closed_all) / closed_wall, 1)
        if closed_wall > 0 else 0.0,
        "n_requests": len(closed_all),
    })
    concurrency = args.concurrency if args.concurrency != 4 else 16
    run = mixed_open_loop_run(
        base, pool, users, [1], target_qps=args.target_qps,
        requests=args.requests, ks=ks, rank_every=1,
        concurrency=concurrency)
    book = run["rank"]
    errors.extend(book["errors"])
    shed_rate = (book["shed"] / book["offered"]) if book["offered"] else 0.0
    corrected_p99 = _percentile(book["corrected_ms"], 99)
    health = _http_json(base + "/healthz")
    metrics1 = _scrape_metrics(base)
    results.append({
        "metric": "serving_ranked_open_loop_latency_ms",
        "value": round(_percentile(book["corrected_ms"], 50), 3),
        "unit": "ms p50 (open-loop GET /rank, latency-corrected from "
                "schedule; 429 sheds excluded, reported as shed_rate)",
        "corrected_p50_ms": round(_percentile(book["corrected_ms"], 50), 3),
        "corrected_p99_ms": round(corrected_p99, 3),
        "uncorrected_p99_ms": round(
            _percentile(book["uncorrected_ms"], 99), 3),
        "target_qps": args.target_qps,
        "achieved_qps": round(len(book["corrected_ms"]) / run["wall_s"], 1)
        if run["wall_s"] > 0 else 0.0,
        "n_requests": len(book["corrected_ms"]),
        "n_shed": book["shed"],
        "shed_rate": round(shed_rate, 4),
        "n_errors": len(book["errors"]),
        "ks": ks,
        "rank_items": health["rank"]["items"],
        "recompiles_during_load": health["rank"]["compiles"]
        - rank_compiles0,
    })
    slo_line = None
    if args.slo_p99_ms is not None:
        slo_line = {"metric": "serving_slo_gate", "workload": "rank"}
        slo_line.update(slo_gate_verdict(corrected_p99, args.slo_p99_ms,
                                         shed_rate=shed_rate))
        results.append(slo_line)
    parity_failures = []
    if server is not None and metrics1 is not None:
        from photon_ml_tpu.telemetry.prometheus import series_value

        # in-process run: the server's /rank books must match the
        # client's exactly (the request-latency histogram excludes sheds
        # by contract; reconnect-served requests were served once)
        done = len(closed_all) + len(book["corrected_ms"]) \
            + book["reconnected"]
        hist = int(series_value(metrics1,
                                "photon_rank_request_latency_seconds_count")
                   - series_value(metrics0 or {},
                                  "photon_rank_request_latency_seconds_count"))
        if hist != done:
            parity_failures.append(
                f"photon_rank_request_latency_seconds counted {hist} "
                f"requests, client completed {done}")
        k_count = int(series_value(metrics1, "photon_rank_k_count")
                      - series_value(metrics0 or {}, "photon_rank_k_count"))
        if k_count != done:
            parity_failures.append(
                f"photon_rank_k counted {k_count}, client completed {done}")
    if metrics1 is not None:
        stages = stage_breakdown(metrics0, metrics1)
        if stages:
            results.append({
                "metric": "serving_stage_breakdown",
                "value": stages.get("execute", {}).get("p50_ms", 0.0),
                "unit": "ms p50 of the execute stage "
                        "(photon_serving_stage_seconds deltas)",
                "stages": stages,
            })
    for r in results:
        print(json.dumps(r), flush=True)
    head = results[0]
    print(json.dumps({
        "metric": "suite_summary",
        "value": head["value"],
        "unit": head["unit"],
        "p99_ms": results[1]["corrected_p99_ms"],
        "zero_recompiles": results[1]["recompiles_during_load"] == 0,
        "metrics_parity": (not parity_failures) if metrics1 is not None
        else None,
        "slo_verdict": slo_line.get("verdict") if slo_line else None,
        "shed_rate": results[1]["shed_rate"],
        "n_errors": len(errors),
        "wall_s": round(closed_wall + run["wall_s"], 2),
    }), flush=True)
    if server is not None:
        server.stop()
    if errors:
        raise SystemExit(f"{len(errors)} failed requests, "
                         f"first: {errors[0]}")
    if parity_failures:
        raise SystemExit("server-side /metrics disagree with the "
                         "client's measurements: "
                         + "; ".join(parity_failures))
    if slo_line is not None and slo_line.get("verdict") == "regression":
        raise SystemExit(
            f"p99 SLO gate (/rank): corrected p99 "
            f"{slo_line['corrected_p99_ms']} ms > SLO "
            f"{slo_line['slo_p99_ms']} ms")


def run_fleet(args) -> None:
    """``--mode fleet``: open-loop load through a router over N local
    entity-sharded hosts (cli/serve_fleet.py) — shed classification, the
    SLO gate and the zero-recompile assert all reused from the
    single-host bench; the recompile count sums over every host. Prints
    the same one-JSON-line-per-metric artifact."""
    from photon_ml_tpu.cli.serve_fleet import build_fleet

    if not (args.model_dir and args.feature_shards):
        raise SystemExit("--mode fleet spawns its own fleet: --model-dir "
                         "and --feature-shards are required")
    fleet_argv = [
        "--model-dir", args.model_dir,
        "--feature-shards", args.feature_shards,
        "--port", "0", "--max-wait-ms", str(args.max_wait_ms),
        "--fleet-shards", str(args.fleet_shards),
        "--replicas", str(args.replicas),
        "--hedge-delay-ms", str(args.hedge_delay_ms),
    ]
    if args.max_queue is not None:
        fleet_argv += ["--max-queue", str(args.max_queue)]
    if args.rank_item_coordinate:
        fleet_argv += ["--rank-item-coordinate", args.rank_item_coordinate,
                       "--rank-max-k", str(args.rank_max_k)]
    fleet = build_fleet(fleet_argv)
    base = fleet.url
    try:
        wait_ready(base)
        pool = fleet_request_pool(args, fleet)
        sizes = [int(s) for s in args.batch_sizes.split(",") if s]
        compiles0 = [_http_json(h + "/healthz")["compiles"]
                     for h in fleet.host_urls()]
        concurrency = args.concurrency if args.concurrency != 4 else 16
        metrics0 = _scrape_process_metrics()
        run = open_loop_run(base, pool, sizes,
                            target_qps=args.target_qps,
                            requests=args.requests,
                            concurrency=concurrency)
        metrics1 = _scrape_process_metrics()
        compiles1 = [_http_json(h + "/healthz")["compiles"]
                     for h in fleet.host_urls()]
        health = _http_json(base + "/healthz")
    finally:
        fleet.stop()
    elastic = fleet_elastic_extras(metrics0, metrics1, run["offered"])
    shed_rate = run["shed"] / run["offered"] if run["offered"] else 0.0
    corrected_p99 = _percentile(run["corrected_ms"], 99)
    results = [{
        "metric": "serving_fleet_open_loop_latency_ms",
        "value": round(_percentile(run["corrected_ms"], 50), 3),
        "unit": "ms p50 (open-loop POST /score through the fleet router "
                "at N local hosts, latency-corrected from schedule; 429 "
                "sheds excluded, reported as shed_rate)",
        "corrected_p50_ms": round(_percentile(run["corrected_ms"], 50), 3),
        "corrected_p99_ms": round(corrected_p99, 3),
        "uncorrected_p99_ms": round(
            _percentile(run["uncorrected_ms"], 99), 3),
        "target_qps": args.target_qps,
        "achieved_qps": round(run["achieved_qps"], 1),
        "n_requests": len(run["corrected_ms"]),
        "n_shed": run["shed"],
        "shed_rate": round(shed_rate, 4),
        "n_errors": len(run["errors"]),
        "n_reconnected": run["reconnected"],
        "n_shards": health["n_shards"],
        "host_status": [h.get("status") for h in health["hosts"]],
        "replicas": args.replicas,
        "hedge_rate": elastic["hedge_rate"],
        "hedges": elastic["hedges"],
        "replica_retries": elastic["replica_retries"],
        "reshard_epochs": elastic["reshard_epochs"],
        # the fleet activation/zero-recompile story: per-host compile
        # deltas across the load window must all be zero
        "recompiles_during_load": [c1 - c0 for c0, c1
                                   in zip(compiles0, compiles1)],
    }]
    slo_line = None
    if args.slo_p99_ms is not None:
        slo_line = {"metric": "serving_slo_gate", "workload": "fleet"}
        slo_line.update(slo_gate_verdict(corrected_p99, args.slo_p99_ms,
                                         shed_rate=shed_rate))
        results.append(slo_line)
    for r in results:
        print(json.dumps(r), flush=True)
    head = results[0]
    print(json.dumps({
        "metric": "suite_summary",
        "value": head["value"],
        "unit": head["unit"],
        "p99_ms": head["corrected_p99_ms"],
        "zero_recompiles": all(c == 0
                               for c in head["recompiles_during_load"]),
        "slo_verdict": slo_line.get("verdict") if slo_line else None,
        "shed_rate": head["shed_rate"],
        "n_errors": len(run["errors"]),
        "wall_s": round(run["wall_s"], 2),
    }), flush=True)
    if run["errors"]:
        raise SystemExit(f"{len(run['errors'])} failed requests, "
                         f"first: {run['errors'][0]}")
    if slo_line is not None and slo_line.get("verdict") == "regression":
        raise SystemExit(
            f"p99 SLO gate (fleet): corrected p99 "
            f"{slo_line['corrected_p99_ms']} ms > SLO "
            f"{slo_line['slo_p99_ms']} ms")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--model-dir")
    p.add_argument("--feature-shards")
    p.add_argument("--url", help="bench an already-running server instead "
                                 "of spawning one in-process")
    p.add_argument("--data", help="avro file of records to replay "
                                  "(default: synthesize from the model)")
    p.add_argument("--mode", choices=["closed", "open", "ranked", "fleet"],
                   default="closed",
                   help="closed = workers re-send on completion (hides "
                        "coordinated omission; percentiles labeled "
                        "closed_loop_*); open = fixed --target-qps "
                        "schedule with latency-corrected percentiles; "
                        "ranked = GET /rank closed-loop k sweep + "
                        "open-loop load with shed classification; "
                        "fleet = open-loop /score through a router over "
                        "--fleet-shards local entity-sharded hosts "
                        "(serve_fleet), same shed classification + SLO "
                        "gate")
    p.add_argument("--target-qps", type=float, default=100.0,
                   help="open-loop arrival rate (requests/s)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="open-loop p99 SLO on the CORRECTED percentile; "
                        "emits a bench_gate ok/regression verdict and "
                        "exits 1 on regression")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop worker threads; open-loop max "
                        "in-flight requests (default 16 there)")
    p.add_argument("--batch-sizes", default="1,1,1,2,4,8",
                   help="cycled per request (skew toward singles, like "
                        "real traffic)")
    p.add_argument("--pool", type=int, default=256,
                   help="synthetic request pool size")
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--max-queue", type=int, default=None,
                   help="admission-control queue bound passed through to "
                        "the in-process server (serve_game --max-queue); "
                        "saturating it turns overload into 429 sheds "
                        "reported as shed_rate instead of latency")
    p.add_argument("--rank-item-coordinate", default=None,
                   help="enable /rank on the in-process server "
                        "(serve_game --rank-item-coordinate) — required "
                        "for --mode ranked unless --url points at a "
                        "rank-enabled server")
    p.add_argument("--rank-max-k", type=int, default=128,
                   help="serve_game --rank-max-k for the in-process "
                        "server")
    p.add_argument("--rank-ks", default="1,10,64",
                   help="comma-separated k sweep for --mode ranked "
                        "(each k is clamped by the server's max)")
    p.add_argument("--fleet-shards", type=int, default=2,
                   help="--mode fleet: entity-sharded hosts behind the "
                        "in-process router (serve_fleet --fleet-shards)")
    p.add_argument("--replicas", type=int, default=1,
                   help="--mode fleet: replica group size per shard "
                        "(serve_fleet --replicas; R>=2 enables replica "
                        "retry + hedged fan-out)")
    p.add_argument("--hedge-delay-ms", type=float, default=0.0,
                   help="--mode fleet: fixed hedge delay in ms (0 = "
                        "adaptive p99-derived delay; ignored at R=1)")
    args = p.parse_args(argv)

    if args.mode == "fleet":
        # the fleet workload owns its whole artifact (router spawn,
        # per-host recompile deltas, SLO gate)
        run_fleet(args)
        return

    server = None
    server_events = []
    if args.url:
        base = args.url.rstrip("/")
    else:
        if not (args.model_dir and args.feature_shards):
            raise SystemExit("--model-dir and --feature-shards are "
                             "required without --url")
        from photon_ml_tpu.cli.serve_game import build_server
        from photon_ml_tpu.events import GLOBAL_BUS

        GLOBAL_BUS.subscribe(
            lambda e: server_events.append(e)
            if e.name == "serving_request" else None)
        argv_server = [
            "--model-dir", args.model_dir,
            "--feature-shards", args.feature_shards,
            "--port", "0", "--max-wait-ms", str(args.max_wait_ms),
        ]
        if args.max_queue is not None:
            argv_server += ["--max-queue", str(args.max_queue)]
        if args.rank_item_coordinate:
            argv_server += ["--rank-item-coordinate",
                            args.rank_item_coordinate,
                            "--rank-max-k", str(args.rank_max_k)]
        server = build_server(argv_server).start()
        base = server.url

    # readiness, not liveness: warming buckets / loading tables answer
    # /healthz long before they can serve — gate the load on /readyz
    wait_ready(base)
    pool = _request_pool(args, server)
    if args.mode == "ranked":
        # the ranked workload owns its whole artifact (per-k sweep,
        # open-loop shed classification, /rank metric parity)
        run_ranked(args, server, base, pool)
        return
    cold_refs = None
    if server is not None:
        # per-pool-record count of entity references landing on a store's
        # zero fallback row (unknown or missing id) — the client-side
        # ground truth the scraped photon_quality_cold_start_total delta
        # must match exactly for an in-process run
        stores = list(server.service.registry.active().stores.values())

        def _cold_count(rec):
            meta = rec.get("metadataMap") or {}
            return sum(
                int(store.rows_for(
                    [meta.get(store.random_effect_type)])[0]
                    == store.fallback_row)
                for store in stores)

        cold_refs = [_cold_count(r) for r in pool]
    sizes = [int(s) for s in args.batch_sizes.split(",") if s]
    compiles0 = _http_json(base + "/healthz")["compiles"]
    metrics0 = _scrape_metrics(base)

    latencies: list[float] = []
    errors: list[str] = []
    results: list[dict] = []
    slo_line = None

    if args.mode == "open":
        concurrency = args.concurrency if args.concurrency != 4 else 16
        run = open_loop_run(base, pool, sizes,
                            target_qps=args.target_qps,
                            requests=args.requests,
                            concurrency=concurrency)
        latencies = run["uncorrected_ms"]
        errors = run["errors"]
        wall = run["wall_s"]
        rows = run["rows"]
        shed_rate = run["shed"] / run["offered"] if run["offered"] else 0.0
        corrected_p99 = _percentile(run["corrected_ms"], 99)
        health = _http_json(base + "/healthz")
        metrics1 = _scrape_metrics(base)
        results.append({
            "metric": "serving_open_loop_latency_ms",
            "value": round(_percentile(run["corrected_ms"], 50), 3),
            "unit": "ms p50 (open-loop, latency-corrected from schedule; "
                    "429 sheds excluded, reported as shed_rate)",
            "corrected_p50_ms": round(
                _percentile(run["corrected_ms"], 50), 3),
            "corrected_p99_ms": round(corrected_p99, 3),
            "uncorrected_p50_ms": round(_percentile(latencies, 50), 3),
            "uncorrected_p99_ms": round(_percentile(latencies, 99), 3),
            "target_qps": args.target_qps,
            "achieved_qps": round(run["achieved_qps"], 1),
            "rows_per_sec": round(rows / wall, 1) if wall > 0 else 0.0,
            "n_requests": len(run["corrected_ms"]),
            "n_shed": run["shed"],
            "shed_rate": round(shed_rate, 4),
            "n_errors": len(errors),
            # served after a bounded reconnect-on-reset (backlog RST
            # under CPU contention): excluded from the percentiles
            "n_reconnected": run["reconnected"],
            "concurrency": concurrency,
            "batch_sizes": sizes,
            "recompiles_during_load": health["compiles"] - compiles0,
            "version": health["version"],
        })
        if metrics1 is not None:
            stages = stage_breakdown(metrics0, metrics1)
            if stages:
                results.append({
                    "metric": "serving_stage_breakdown",
                    "value": stages.get("execute", {}).get("p50_ms", 0.0),
                    "unit": "ms p50 of the execute stage "
                            "(photon_serving_stage_seconds deltas)",
                    "stages": stages,
                })
        if args.slo_p99_ms is not None:
            slo_line = {"metric": "serving_slo_gate"}
            slo_line.update(slo_gate_verdict(corrected_p99,
                                             args.slo_p99_ms,
                                             shed_rate=shed_rate))
            results.append(slo_line)
    else:
        lock = threading.Lock()
        counter = {"i": 0}
        cold_sent = {"n": 0}

        def worker():
            while True:
                with lock:
                    i = counter["i"]
                    if i >= args.requests:
                        return
                    counter["i"] += 1
                size = sizes[i % len(sizes)]
                recs = [pool[(i + j) % len(pool)] for j in range(size)]
                t0 = time.perf_counter()
                try:
                    out = _http_json(base + "/score", {"records": recs})
                    assert len(out["scores"]) == size
                except Exception as e:
                    with lock:
                        errors.append(repr(e))
                    continue
                with lock:
                    latencies.append((time.perf_counter() - t0) * 1e3)
                    if cold_refs is not None:
                        cold_sent["n"] += sum(
                            cold_refs[(i + j) % len(pool)]
                            for j in range(size))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker)
                   for _ in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        health = _http_json(base + "/healthz")
        metrics1 = _scrape_metrics(base)

        rows = sum(sizes[i % len(sizes)] for i in range(args.requests))
        results.append({
            "metric": "serving_score_latency_ms",
            # closed_loop_* are the honest names (this methodology hides
            # coordinated omission); value/p99_ms stay as aliases so
            # bench_gate baselines keep comparing round over round
            "value": round(_percentile(latencies, 50), 3),
            "unit": "ms p50 (closed-loop client-observed, HTTP included; "
                    "hides coordinated omission — see --mode open)",
            "closed_loop_p50_ms": round(_percentile(latencies, 50), 3),
            "closed_loop_p99_ms": round(_percentile(latencies, 99), 3),
            "p99_ms": round(_percentile(latencies, 99), 3),
            "requests_per_sec": round(len(latencies) / wall, 1),
            "rows_per_sec": round(rows / wall, 1),
            "n_requests": len(latencies),
            "n_errors": len(errors),
            "concurrency": args.concurrency,
            "batch_sizes": sizes,
            "recompiles_during_load": health["compiles"] - compiles0,
            "version": health["version"],
        })
        if server_events:
            sl = [e.payload["latency_ms"] for e in server_events]
            results.append({
                "metric": "serving_server_latency_ms",
                "value": round(_percentile(sl, 50), 3),
                "unit": "ms p50 (closed-loop server-side, via EventBus "
                        "serving_request)",
                "closed_loop_p50_ms": round(_percentile(sl, 50), 3),
                "closed_loop_p99_ms": round(_percentile(sl, 99), 3),
                "p99_ms": round(_percentile(sl, 99), 3),
                "n_events": len(sl),
            })
    parity_failures: list[str] = []
    if metrics1 is not None:
        from photon_ml_tpu.telemetry.metrics import quantile_from_buckets
        from photon_ml_tpu.telemetry.prometheus import series_value

        def delta(name, labels=None):
            return (series_value(metrics1, name, labels)
                    - series_value(metrics0 or {}, name, labels))

        # bucket series are CUMULATIVE, so their per-scrape deltas are too
        uppers, cum, hist_count = _histogram_delta(
            metrics0, metrics1, "photon_serving_request_latency_seconds")
        q = (lambda p: round(
            quantile_from_buckets(uppers, cum, p) * 1e3, 3)) \
            if cum and cum[-1] else (lambda p: 0.0)
        # the serving traces count under the system-wide compile family
        # (telemetry/profiling.py) since the profiling layer landed
        recompiles_metric = int(delta("photon_compiles_total",
                                      {"fn": "serving.score"}))
        requests_metric = int(delta("photon_serving_requests_total"))
        scrape_line = {
            "metric": "serving_metrics_scrape",
            "value": q(0.50),
            "unit": "ms p50 (server histogram, bucket-interpolated)",
            "p99_ms": q(0.99),
            "histogram_count": hist_count,
            "requests_total": requests_metric,
            "recompiles_total": recompiles_metric,
            "active_version": series_value(
                metrics1, "photon_model_active_version"),
        }
        if args.mode == "closed":
            stages = stage_breakdown(metrics0, metrics1)
            if stages:
                scrape_line["stages"] = stages
        results.append(scrape_line)
        # model-quality families (quality/monitor.py): the engine-side
        # accumulation across the load window
        def _labeled_delta(name, label):
            out = {}
            for labels, v1 in metrics1.get(name, []):
                if label in labels:
                    v0 = series_value(metrics0 or {}, name,
                                      {label: labels[label]})
                    out[labels[label]] = v1 - v0
            return out

        cold_by_cid = _labeled_delta("photon_quality_cold_start_total",
                                     "coordinate")
        quality_cold = int(sum(cold_by_cid.values()))
        quality_rows = int(delta("photon_quality_scored_rows_total"))
        results.append({
            "metric": "serving_quality_metrics",
            "value": quality_cold,
            "unit": "cold-start entity refs "
                    "(photon_quality_cold_start_total delta)",
            "cold_start_by_coordinate": {k: int(v)
                                         for k, v in cold_by_cid.items()},
            "scored_rows": quality_rows,
            "client_cold_sent": (cold_sent["n"]
                                 if args.mode == "closed"
                                 and cold_refs is not None else None),
        })
        if server is not None:
            # in-process run = the bench is the only traffic, so the
            # server's own books must match the client's exactly
            # (reconnect-served open-loop requests were served once)
            n_done = (len(latencies) if args.mode == "closed"
                      else len(run["corrected_ms"]) + run["reconnected"])
            if args.mode == "open":
                # every client-observed 429 is exactly one server-side
                # shed (and vice versa) — the admission-control books
                shed_metric = int(sum(_labeled_delta(
                    "photon_shed_total", "reason").values()))
                if shed_metric != run["shed"]:
                    parity_failures.append(
                        f"photon_shed_total moved {shed_metric}, client "
                        f"observed {run['shed']} 429 responses")
            if (args.mode == "closed" and cold_refs is not None
                    and quality_cold != cold_sent["n"]):
                parity_failures.append(
                    f"photon_quality_cold_start_total moved "
                    f"{quality_cold}, client sent {cold_sent['n']} "
                    f"unknown-entity references")
            if requests_metric != n_done:
                parity_failures.append(
                    f"requests_total moved {requests_metric}, client "
                    f"completed {n_done}")
            if hist_count != n_done:
                parity_failures.append(
                    f"latency histogram counted {hist_count} requests, "
                    f"client completed {n_done}")
            if recompiles_metric != health["compiles"] - compiles0:
                parity_failures.append(
                    f"recompiles_total moved {recompiles_metric}, healthz "
                    f"compile counter moved {health['compiles'] - compiles0}")
    for r in results:
        print(json.dumps(r), flush=True)
    head = results[0]
    print(json.dumps({
        "metric": "suite_summary",
        "value": head["value"],
        "unit": head["unit"],
        "p99_ms": head.get("corrected_p99_ms", head.get("p99_ms")),
        "zero_recompiles": head["recompiles_during_load"] == 0,
        "metrics_parity": not parity_failures if metrics1 is not None
        else None,
        "slo_verdict": slo_line.get("verdict") if slo_line else None,
        "shed_rate": head.get("shed_rate"),
        "n_errors": len(errors),
        "wall_s": round(wall, 2),
    }), flush=True)
    if server is not None:
        server.stop()
    if errors:
        raise SystemExit(f"{len(errors)} failed requests, first: {errors[0]}")
    if parity_failures:
        raise SystemExit("server-side /metrics disagree with the client's "
                         "measurements: " + "; ".join(parity_failures))
    if slo_line is not None and slo_line.get("verdict") == "regression":
        cause = slo_line.get("cause", "slow")
        raise SystemExit(
            f"p99 SLO gate: corrected p99 "
            f"{slo_line['corrected_p99_ms']} ms > SLO "
            f"{slo_line['slo_p99_ms']} ms (verdict: regression, cause: "
            f"{cause}"
            + (f", shed_rate {slo_line['shed_rate']}" if cause == "shedding"
               else "") + ")")


if __name__ == "__main__":
    main()
