"""Load generator / latency bench for the online serving subsystem.

Spins up an in-process :class:`GameServer` over a trained GAME model (or
targets an already-running server via ``--url``), replays request traffic at
mixed batch sizes from worker threads, and reports:

- ``serving_score_latency_ms`` — p50/p99 end-to-end HTTP latency plus
  throughput (requests/s, rows/s),
- the engine recompile count across the loaded phase (the zero-recompile
  contract: after warmup it must not move — asserted by
  tests/test_serving.py, *reported* here),
- per-request metrics stream: the service posts one ``serving_request``
  event per scored request on the EventBus; the bench subscribes a listener
  and folds them into the summary (server-side latency vs. the
  client-observed one),
- a ``/metrics`` scrape (before and after the load) folding the SERVER'S
  own Prometheus histogram into the report: request-latency quantiles
  estimated from the bucket deltas, the recompile counter delta, and —
  for in-process runs, where the bench is the only traffic — parity
  assertions between the scraped counters and the client-side tallies
  (requests counted == requests sent, recompiles metric == healthz
  compiles delta, histogram count == scored requests),
- the ``photon_quality_*`` model-quality families (quality/monitor.py):
  scored-row and cold-start counter deltas across the load, with a HARD
  parity assert for in-process runs that the server's cold-start counter
  moved by exactly the client-side tally of unknown-entity references
  the bench sent (computed per record against the store's own row map).

Output: one JSON line per metric + a terminal ``suite_summary`` line, the
same artifact shape as bench.py.

Usage::

    python tools/bench_serving.py --model-dir out/ \
        --feature-shards 'global=fixed|intercept,user=user|noIntercept' \
        --data val.avro --requests 500 --concurrency 4
"""

from __future__ import annotations

import argparse
import json
import threading
import time
import urllib.request


def _percentile(xs, q):
    import numpy as np

    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


def _http_json(url: str, payload=None, timeout=60.0):
    if payload is None:
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _scrape_metrics(base: str):
    """Parsed /metrics snapshot, or None against a server without the
    endpoint (pre-telemetry builds)."""
    from photon_ml_tpu.telemetry.prometheus import parse_text

    try:
        with urllib.request.urlopen(base + "/metrics", timeout=60) as resp:
            return parse_text(resp.read().decode())
    except Exception:
        return None


def _histogram_delta(m0, m1, name: str):
    """(uppers, cumulative-count deltas, count delta) for one label-free
    histogram between two scrapes — the load window's own distribution."""
    import math

    from photon_ml_tpu.telemetry.prometheus import series_value

    buckets1 = m1.get(name + "_bucket", [])
    uppers, deltas = [], []
    for labels, v1 in buckets1:
        le = labels.get("le")
        v0 = series_value(m0 or {}, name + "_bucket", {"le": le})
        uppers.append(math.inf if le == "+Inf" else float(le))
        deltas.append(int(v1 - v0))
    order = sorted(range(len(uppers)), key=lambda i: uppers[i])
    uppers = [uppers[i] for i in order]
    deltas = [deltas[i] for i in order]
    count = (series_value(m1, name + "_count")
             - series_value(m0 or {}, name + "_count"))
    return uppers[:-1], deltas, int(count)


def _request_pool(args, server):
    """Records to replay: --data avro file when given, else synthetic
    records drawn from the model's own feature/entity universe (plus a
    slice of unseen entities — the cold-start path serves too)."""
    if args.data:
        from photon_ml_tpu.io.avro import iter_avro_file

        records = list(iter_avro_file(args.data))
        if not records:
            raise SystemExit(f"--data {args.data!r} holds no records")
        return records
    if server is None:
        raise SystemExit("--data is required with --url (a remote bench "
                         "can't introspect the model's feature space)")
    import numpy as np

    from photon_ml_tpu.types import NAME_TERM_DELIMITER

    sm = server.service.registry.active()
    rng = np.random.default_rng(7)
    records = []
    stores = list(sm.stores.values())
    for i in range(args.pool):
        feats = []
        for cfg in sm.engine.shard_configs:
            names = [k for k in sm.index_maps[cfg.shard_id].names()
                     if not k.startswith("(INTERCEPT)")]
            take = rng.choice(len(names), size=min(6, len(names)),
                              replace=False)
            for t in take:
                name, _, term = names[int(t)].partition(NAME_TERM_DELIMITER)
                feats.append({"name": name, "term": term,
                              "value": float(rng.normal())})
        meta = {}
        for store in stores:
            ids = list(store.row_of_id)
            # ~10% unseen entities: the fallback path is part of traffic
            if ids and rng.random() > 0.1:
                meta[store.random_effect_type] = ids[int(rng.integers(len(ids)))]
            else:
                meta[store.random_effect_type] = f"__cold_{i}"
        records.append({"features": feats, "metadataMap": meta,
                        "offset": None})
    return records


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--model-dir")
    p.add_argument("--feature-shards")
    p.add_argument("--url", help="bench an already-running server instead "
                                 "of spawning one in-process")
    p.add_argument("--data", help="avro file of records to replay "
                                  "(default: synthesize from the model)")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--batch-sizes", default="1,1,1,2,4,8",
                   help="cycled per request (skew toward singles, like "
                        "real traffic)")
    p.add_argument("--pool", type=int, default=256,
                   help="synthetic request pool size")
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    args = p.parse_args(argv)

    server = None
    server_events = []
    if args.url:
        base = args.url.rstrip("/")
    else:
        if not (args.model_dir and args.feature_shards):
            raise SystemExit("--model-dir and --feature-shards are "
                             "required without --url")
        from photon_ml_tpu.cli.serve_game import build_server
        from photon_ml_tpu.events import GLOBAL_BUS

        GLOBAL_BUS.subscribe(
            lambda e: server_events.append(e)
            if e.name == "serving_request" else None)
        server = build_server([
            "--model-dir", args.model_dir,
            "--feature-shards", args.feature_shards,
            "--port", "0", "--max-wait-ms", str(args.max_wait_ms),
        ]).start()
        base = server.url

    pool = _request_pool(args, server)
    cold_refs = None
    if server is not None:
        # per-pool-record count of entity references landing on a store's
        # zero fallback row (unknown or missing id) — the client-side
        # ground truth the scraped photon_quality_cold_start_total delta
        # must match exactly for an in-process run
        stores = list(server.service.registry.active().stores.values())

        def _cold_count(rec):
            meta = rec.get("metadataMap") or {}
            return sum(
                int(store.rows_for(
                    [meta.get(store.random_effect_type)])[0]
                    == store.fallback_row)
                for store in stores)

        cold_refs = [_cold_count(r) for r in pool]
    sizes = [int(s) for s in args.batch_sizes.split(",") if s]
    compiles0 = _http_json(base + "/healthz")["compiles"]
    metrics0 = _scrape_metrics(base)

    latencies: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()
    counter = {"i": 0}
    cold_sent = {"n": 0}

    def worker():
        while True:
            with lock:
                i = counter["i"]
                if i >= args.requests:
                    return
                counter["i"] += 1
            size = sizes[i % len(sizes)]
            recs = [pool[(i + j) % len(pool)] for j in range(size)]
            t0 = time.perf_counter()
            try:
                out = _http_json(base + "/score", {"records": recs})
                assert len(out["scores"]) == size
            except Exception as e:
                with lock:
                    errors.append(repr(e))
                continue
            with lock:
                latencies.append((time.perf_counter() - t0) * 1e3)
                if cold_refs is not None:
                    cold_sent["n"] += sum(
                        cold_refs[(i + j) % len(pool)]
                        for j in range(size))

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    health = _http_json(base + "/healthz")
    metrics1 = _scrape_metrics(base)

    rows = sum(sizes[i % len(sizes)] for i in range(args.requests))
    results = [{
        "metric": "serving_score_latency_ms",
        "value": round(_percentile(latencies, 50), 3),
        "unit": "ms p50 (client-observed, HTTP included)",
        "p99_ms": round(_percentile(latencies, 99), 3),
        "requests_per_sec": round(len(latencies) / wall, 1),
        "rows_per_sec": round(rows / wall, 1),
        "n_requests": len(latencies),
        "n_errors": len(errors),
        "concurrency": args.concurrency,
        "batch_sizes": sizes,
        "recompiles_during_load": health["compiles"] - compiles0,
        "version": health["version"],
    }]
    if server_events:
        sl = [e.payload["latency_ms"] for e in server_events]
        results.append({
            "metric": "serving_server_latency_ms",
            "value": round(_percentile(sl, 50), 3),
            "unit": "ms p50 (server-side, via EventBus serving_request)",
            "p99_ms": round(_percentile(sl, 99), 3),
            "n_events": len(sl),
        })
    parity_failures: list[str] = []
    if metrics1 is not None:
        from photon_ml_tpu.telemetry.metrics import quantile_from_buckets
        from photon_ml_tpu.telemetry.prometheus import series_value

        def delta(name, labels=None):
            return (series_value(metrics1, name, labels)
                    - series_value(metrics0 or {}, name, labels))

        # bucket series are CUMULATIVE, so their per-scrape deltas are too
        uppers, cum, hist_count = _histogram_delta(
            metrics0, metrics1, "photon_serving_request_latency_seconds")
        q = (lambda p: round(
            quantile_from_buckets(uppers, cum, p) * 1e3, 3)) \
            if cum and cum[-1] else (lambda p: 0.0)
        # the serving traces count under the system-wide compile family
        # (telemetry/profiling.py) since the profiling layer landed
        recompiles_metric = int(delta("photon_compiles_total",
                                      {"fn": "serving.score"}))
        requests_metric = int(delta("photon_serving_requests_total"))
        results.append({
            "metric": "serving_metrics_scrape",
            "value": q(0.50),
            "unit": "ms p50 (server histogram, bucket-interpolated)",
            "p99_ms": q(0.99),
            "histogram_count": hist_count,
            "requests_total": requests_metric,
            "recompiles_total": recompiles_metric,
            "active_version": series_value(
                metrics1, "photon_model_active_version"),
        })
        # model-quality families (quality/monitor.py): the engine-side
        # accumulation across the load window
        def _labeled_delta(name, label):
            out = {}
            for labels, v1 in metrics1.get(name, []):
                if label in labels:
                    v0 = series_value(metrics0 or {}, name,
                                      {label: labels[label]})
                    out[labels[label]] = v1 - v0
            return out

        cold_by_cid = _labeled_delta("photon_quality_cold_start_total",
                                     "coordinate")
        quality_cold = int(sum(cold_by_cid.values()))
        quality_rows = int(delta("photon_quality_scored_rows_total"))
        results.append({
            "metric": "serving_quality_metrics",
            "value": quality_cold,
            "unit": "cold-start entity refs "
                    "(photon_quality_cold_start_total delta)",
            "cold_start_by_coordinate": {k: int(v)
                                         for k, v in cold_by_cid.items()},
            "scored_rows": quality_rows,
            "client_cold_sent": (cold_sent["n"] if cold_refs is not None
                                 else None),
        })
        if server is not None:
            # in-process run = the bench is the only traffic, so the
            # server's own books must match the client's exactly
            if cold_refs is not None and quality_cold != cold_sent["n"]:
                parity_failures.append(
                    f"photon_quality_cold_start_total moved "
                    f"{quality_cold}, client sent {cold_sent['n']} "
                    f"unknown-entity references")
            if requests_metric != len(latencies):
                parity_failures.append(
                    f"requests_total moved {requests_metric}, client "
                    f"completed {len(latencies)}")
            if hist_count != len(latencies):
                parity_failures.append(
                    f"latency histogram counted {hist_count} requests, "
                    f"client completed {len(latencies)}")
            if recompiles_metric != health["compiles"] - compiles0:
                parity_failures.append(
                    f"recompiles_total moved {recompiles_metric}, healthz "
                    f"compile counter moved {health['compiles'] - compiles0}")
    for r in results:
        print(json.dumps(r), flush=True)
    print(json.dumps({
        "metric": "suite_summary",
        "value": results[0]["value"],
        "unit": results[0]["unit"],
        "p99_ms": results[0]["p99_ms"],
        "zero_recompiles": results[0]["recompiles_during_load"] == 0,
        "metrics_parity": not parity_failures if metrics1 is not None
        else None,
        "n_errors": len(errors),
        "wall_s": round(wall, 2),
    }), flush=True)
    if server is not None:
        server.stop()
    if errors:
        raise SystemExit(f"{len(errors)} failed requests, first: {errors[0]}")
    if parity_failures:
        raise SystemExit("server-side /metrics disagree with the client's "
                         "measurements: " + "; ".join(parity_failures))


if __name__ == "__main__":
    main()
