#!/usr/bin/env python
"""Critical-path performance report for a ``--telemetry-dir`` run.

The run's artifacts already hold everything needed to answer "where did
the wall-clock go": ``trace.jsonl`` (the span tree — or
``trace.merged.jsonl`` for a multi-process run) and ``metrics.prom`` (the
registry snapshot — or ``metrics.aggregate.prom`` for the fleet fold).
This tool renders them into one deterministic text report:

- **critical path** — top-k span groups by EXCLUSIVE seconds (a span's
  own wall minus its direct children's), so a fat parent that merely
  contains the work doesn't mask the stage that performs it;
- **compile vs execute** — the profiled-jit accounting
  (``photon_compiles_total{fn}`` / ``photon_compile_seconds_total{fn}`` /
  ``photon_execute_latency_seconds{fn}`` — telemetry/profiling.py), per
  function and total, plus the process-wide XLA pipeline counters that
  catch un-wrapped jits;
- **async I/O overlap** — how much of the ``io.save.*`` / ``io.read.*``
  span time (the background writer/prefetcher pipeline,
  ``io/pipeline.py``) lies hidden under training compute — the line that
  makes the save/ingest overlap provable from artifacts (section present
  only when the trace carries I/O spans);
- **per-coordinate table** — ``cd.step`` spans folded per coordinate with
  the optimizer-iteration counters;
- **serving request path** — the per-stage critical path of a serving
  snapshot (``photon_serving_stage_seconds{stage=...}``: parse →
  queue_wait → batch_assemble → execute → respond) with
  bucket-interpolated p50/p99 per stage plus the end-to-end
  ``photon_serving_request_latency_seconds`` summary and the request-log
  budget counters — the serving counterpart of the training critical
  path (section present only when the snapshot carries serving series);
- **FLOPs/s estimate** — ``photon_flops_total{fn}`` over the execute-sum
  seconds (dispatch-side; a lower bound on device throughput).

Usage::

    python tools/perf_report.py DIR [--top K]

where DIR is the run's ``--telemetry-dir``. Merged/aggregate artifacts are
preferred automatically when present.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Mapping, Optional, Sequence

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from photon_ml_tpu.telemetry import prometheus as tprom  # noqa: E402

#: span attributes that are record plumbing, not user attributes
_RESERVED = ("name", "span_id", "parent_id", "ts", "t0", "t1", "seconds",
             "process")


def load_spans(path: str) -> list[dict]:
    """Span records (``span_id`` non-null) from a trace file; annotations
    are dropped. Each record gets a ``process`` key (0 when absent)."""
    spans = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("span_id") is None:
                continue
            rec.setdefault("process", 0)
            spans.append(rec)
    return spans


def _group_label(span: Mapping) -> str:
    """Aggregation key for the critical path: the span name, plus the
    coordinate attribute when present (cd.step{coordinate=global} is a
    different line of work than cd.step{coordinate=perUser})."""
    if "coordinate" in span:
        return f'{span["name"]}{{coordinate={span["coordinate"]}}}'
    return str(span["name"])


def exclusive_seconds(spans: Sequence[Mapping]) -> dict[tuple, dict]:
    """Per span-group: total, exclusive (total minus direct children) and
    call count. Spans key by (process, span_id) so merged multi-process
    traces fold correctly."""
    child_sum: dict[tuple, float] = {}
    for s in spans:
        if s.get("parent_id") is not None:
            pkey = (s["process"], s["parent_id"])
            child_sum[pkey] = child_sum.get(pkey, 0.0) + float(s["seconds"])
    groups: dict[tuple, dict] = {}
    for s in spans:
        key = (s["process"], _group_label(s))
        g = groups.setdefault(key, {"total": 0.0, "exclusive": 0.0,
                                    "calls": 0})
        own = float(s["seconds"])
        g["total"] += own
        g["exclusive"] += max(
            own - child_sum.get((s["process"], s["span_id"]), 0.0), 0.0)
        g["calls"] += 1
    return groups


def _merge_intervals(intervals: list[tuple[float, float]],
                     ) -> list[tuple[float, float]]:
    out: list[tuple[float, float]] = []
    for lo, hi in sorted(intervals):
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def _overlap_seconds(lo: float, hi: float,
                     merged: list[tuple[float, float]]) -> float:
    return sum(max(0.0, min(hi, b) - max(lo, a)) for a, b in merged)


def io_overlap(spans: Sequence[Mapping]) -> Optional[dict]:
    """How much of the async I/O pipeline's wall was HIDDEN under
    training compute: per class (``save`` = ``io.save.*`` spans, ``read``
    = ``io.read.*`` spans), total span seconds and the fraction of them
    that lies inside the union of train intervals (``cd.sweep`` spans plus
    ``Train*`` stage spans), compared per process via the monotonic
    ``t0``/``t1`` readings. Nested I/O spans (``io.save.part`` under
    ``io.save.model``) count once — only spans whose direct parent is not
    itself an I/O span are summed. None when the trace has no I/O spans."""
    by_id = {(s["process"], s["span_id"]): s for s in spans}
    train: dict[int, list[tuple[float, float]]] = {}
    for s in spans:
        if (s["name"] == "cd.sweep"
                or (s.get("kind") == "stage"
                    and str(s["name"]).startswith("Train"))):
            train.setdefault(s["process"], []).append(
                (float(s["t0"]), float(s["t1"])))
    merged = {p: _merge_intervals(iv) for p, iv in train.items()}
    out = {}
    for cls in ("save", "read"):
        total = hidden = 0.0
        count = 0
        for s in spans:
            if not str(s["name"]).startswith(f"io.{cls}"):
                continue
            parent = by_id.get((s["process"], s.get("parent_id")))
            if parent is not None and str(parent["name"]).startswith("io."):
                continue  # nested I/O span: counted via its parent
            total += float(s["seconds"])
            hidden += _overlap_seconds(float(s["t0"]), float(s["t1"]),
                                       merged.get(s["process"], []))
            count += 1
        if count:
            out[cls] = {"seconds": total, "hidden_seconds": hidden,
                        "spans": count,
                        "hidden_pct": (100.0 * hidden / total
                                       if total > 0 else 0.0)}
    if not out:
        return None
    out["train_wall_s"] = sum(hi - lo for iv in merged.values()
                              for lo, hi in iv)
    return out


def _histogram_quantiles(parsed: Mapping, name: str,
                         match: Optional[Mapping[str, str]] = None,
                         ) -> Optional[dict]:
    """count/total_s/p50/p99 of one histogram series in a snapshot (the
    series whose labels contain ``match``); None when absent/empty."""
    import math

    from photon_ml_tpu.telemetry.metrics import quantile_from_buckets

    match = dict(match or {})
    pairs = []
    for labels, value in parsed.get(name + "_bucket", ()):
        if not all(labels.get(k) == v for k, v in match.items()):
            continue
        le = labels.get("le")
        pairs.append((math.inf if le == "+Inf" else float(le), int(value)))
    if not pairs:
        return None
    pairs.sort(key=lambda p: p[0])
    uppers = [u for u, _ in pairs][:-1]
    cum = [c for _, c in pairs]
    count = cum[-1]
    if count == 0:
        return None
    total = 0.0
    for labels, value in parsed.get(name + "_sum", ()):
        if all(labels.get(k) == v for k, v in match.items()):
            total = value
            break
    return {"count": int(count), "total_s": float(total),
            "p50_ms": quantile_from_buckets(uppers, cum, 0.50) * 1e3,
            "p99_ms": quantile_from_buckets(uppers, cum, 0.99) * 1e3}


def serving_request_path(parsed: Mapping) -> Optional[dict]:
    """The serving snapshot's per-stage critical path: stage histograms
    (``photon_serving_stage_seconds``), the end-to-end request histogram,
    and the request-log budget counters. None when the snapshot carries no
    serving stage series (a training-only run)."""
    stages = {}
    seen = {labels.get("stage")
            for labels, _ in parsed.get(
                "photon_serving_stage_seconds_bucket", ())}
    for stage in sorted(s for s in seen if s):
        q = _histogram_quantiles(parsed, "photon_serving_stage_seconds",
                                 {"stage": stage})
        if q is not None:
            stages[stage] = q
    if not stages:
        return None
    out = {
        "stages": stages,
        "request": _histogram_quantiles(
            parsed, "photon_serving_request_latency_seconds"),
        "reqlog": None,
    }
    reqlog = {}
    for key, series in (("records", "photon_reqlog_records_total"),
                        ("bytes", "photon_reqlog_bytes_total"),
                        ("dropped", "photon_reqlog_dropped_total")):
        samples = parsed.get(series, ())
        if samples:
            reqlog[key] = sum(v for _, v in samples)
    if reqlog:
        out["reqlog"] = {"records": reqlog.get("records", 0),
                         "bytes": reqlog.get("bytes", 0),
                         "dropped": reqlog.get("dropped", 0)}
    return out


def _labeled(parsed: Mapping, series: str, label: str) -> dict[str, float]:
    """{label value: sample value} over one series' samples."""
    out: dict[str, float] = {}
    for labels, value in parsed.get(series, ()):
        if label in labels:
            out[labels[label]] = out.get(labels[label], 0.0) + value
    return out


def _fmt_count(v: float) -> str:
    """Human scale for FLOP/byte totals (deterministic, 3 significant-ish
    digits)."""
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def build_report(spans: Sequence[Mapping], prom_text: str,
                 top: int = 10) -> str:
    """The report text (the CLI prints it; tests golden-compare it)."""
    parsed = tprom.parse_text(prom_text)
    multi = len({s["process"] for s in spans}) > 1 if spans else False
    lines: list[str] = ["== photon performance report =="]

    roots = [s for s in spans if s.get("parent_id") is None]
    wall = sum(float(s["seconds"]) for s in roots)
    root_names = sorted({_group_label(s) for s in roots})
    lines.append(f"wall {wall:.3f} s across {len(roots)} root span(s)"
                 + (f" [{', '.join(root_names)}]" if root_names else ""))

    # --- critical path ----------------------------------------------------
    lines.append("")
    lines.append(f"-- critical path: top {top} span groups by exclusive "
                 f"seconds --")
    groups = exclusive_seconds(spans)
    header = f"{'exclusive_s':>12} {'total_s':>10} {'calls':>6}  span"
    lines.append(header)
    ranked = sorted(groups.items(),
                    key=lambda kv: (-kv[1]["exclusive"], kv[0]))
    for (process, label), g in ranked[:top]:
        tag = f" [proc {process}]" if multi else ""
        lines.append(f"{g['exclusive']:>12.3f} {g['total']:>10.3f} "
                     f"{g['calls']:>6d}  {label}{tag}")
    if not groups:
        lines.append("  (no spans)")

    # --- async I/O overlap -----------------------------------------------
    overlap = io_overlap(spans)
    if overlap is not None:
        lines.append("")
        lines.append("-- async I/O overlap (hidden under train) --")
        lines.append(f"train wall {overlap['train_wall_s']:.3f} s")
        for cls in ("save", "read"):
            if cls in overlap:
                o = overlap[cls]
                lines.append(
                    f"{cls}: {o['seconds']:.3f} s across {o['spans']} "
                    f"span(s), {o['hidden_pct']:.1f}% hidden")

    # --- compile vs execute ----------------------------------------------
    lines.append("")
    lines.append("-- compile vs execute (profiled jits) --")
    compiles = _labeled(parsed, "photon_compiles_total", "fn")
    compile_s = _labeled(parsed, "photon_compile_seconds_total", "fn")
    exec_s = _labeled(parsed, "photon_execute_latency_seconds_sum", "fn")
    exec_n = _labeled(parsed, "photon_execute_latency_seconds_count", "fn")
    flops = _labeled(parsed, "photon_flops_total", "fn")
    bytes_ = _labeled(parsed, "photon_bytes_accessed_total", "fn")
    fns = sorted(set(compiles) | set(exec_n))
    if fns:
        lines.append(f"{'fn':<28} {'compiles':>8} {'compile_s':>10} "
                     f"{'execs':>7} {'execute_s':>10} {'flops':>9} "
                     f"{'GFLOP/s':>8}")
        for fn in fns:
            es = exec_s.get(fn, 0.0)
            fl = flops.get(fn, 0.0)
            rate = (fl / es / 1e9) if es > 0 else 0.0
            lines.append(
                f"{fn:<28} {int(compiles.get(fn, 0)):>8d} "
                f"{compile_s.get(fn, 0.0):>10.3f} "
                f"{int(exec_n.get(fn, 0)):>7d} {es:>10.3f} "
                f"{_fmt_count(fl):>9} {rate:>8.2f}")
        tot_c, tot_e = sum(compile_s.values()), sum(exec_s.values())
        tot_f = sum(flops.values())
        rate = (tot_f / tot_e / 1e9) if tot_e > 0 else 0.0
        lines.append(
            f"{'TOTAL':<28} {int(sum(compiles.values())):>8d} "
            f"{tot_c:>10.3f} {int(sum(exec_n.values())):>7d} "
            f"{tot_e:>10.3f} {_fmt_count(tot_f):>9} {rate:>8.2f}")
        if tot_c + tot_e > 0:
            share = 100.0 * tot_c / (tot_c + tot_e)
            lines.append(f"compile share of (compile+execute): {share:.1f}%"
                         f"  [bytes accessed: "
                         f"{_fmt_count(sum(bytes_.values()))}B]")
    else:
        lines.append("  (no profiled-jit series in snapshot)")
    xla_n = _labeled(parsed, "photon_xla_compiles_total", "phase")
    xla_s = _labeled(parsed, "photon_xla_compile_seconds_total", "phase")
    if xla_s:
        parts = ", ".join(f"{ph} {xla_s.get(ph, 0.0):.3f}s"
                          f"/{int(xla_n.get(ph, 0))}"
                          for ph in ("trace", "lower", "backend")
                          if ph in xla_s or ph in xla_n)
        lines.append(f"process-wide XLA pipeline (any jit): {parts}")

    # --- serving request path --------------------------------------------
    serving = serving_request_path(parsed)
    if serving is not None:
        lines.append("")
        lines.append("-- serving request path (per-stage critical path) --")
        req = serving["request"]
        if req is not None:
            lines.append(
                f"requests {req['count']}: p50 {req['p50_ms']:.3f} ms, "
                f"p99 {req['p99_ms']:.3f} ms "
                f"(photon_serving_request_latency_seconds)")
        lines.append(f"{'stage':<16} {'count':>8} {'total_s':>10} "
                     f"{'p50_ms':>9} {'p99_ms':>9}")
        for stage in ("parse", "queue_wait", "batch_assemble", "execute",
                      "respond"):
            st = serving["stages"].get(stage)
            if st is None:
                continue
            lines.append(f"{stage:<16} {st['count']:>8d} "
                         f"{st['total_s']:>10.3f} {st['p50_ms']:>9.3f} "
                         f"{st['p99_ms']:>9.3f}")
        # stages not in the canonical order still render (forward compat)
        for stage in sorted(serving["stages"]):
            if stage in ("parse", "queue_wait", "batch_assemble",
                         "execute", "respond"):
                continue
            st = serving["stages"][stage]
            lines.append(f"{stage:<16} {st['count']:>8d} "
                         f"{st['total_s']:>10.3f} {st['p50_ms']:>9.3f} "
                         f"{st['p99_ms']:>9.3f}")
        if serving["reqlog"] is not None:
            r = serving["reqlog"]
            lines.append(
                f"request log: {int(r['records'])} records / "
                f"{_fmt_count(r['bytes'])}B written, "
                f"{int(r['dropped'])} dropped")

    # --- per-coordinate table --------------------------------------------
    steps = [s for s in spans if s["name"] == "cd.step"]
    if steps:
        lines.append("")
        lines.append("-- coordinate descent: per-coordinate --")
        iters = _labeled(parsed, "photon_optimizer_iterations_total",
                         "coordinate")
        by_cid: dict[str, list] = {}
        for s in steps:
            by_cid.setdefault(str(s.get("coordinate", "?")), []).append(
                float(s["seconds"]))
        lines.append(f"{'coordinate':<16} {'steps':>6} {'total_s':>10} "
                     f"{'mean_s':>9} {'opt_iters':>10}")
        for cid in sorted(by_cid):
            ss = by_cid[cid]
            lines.append(f"{cid:<16} {len(ss):>6d} {sum(ss):>10.3f} "
                         f"{sum(ss) / len(ss):>9.3f} "
                         f"{int(iters.get(cid, 0)):>10d}")
    return "\n".join(lines) + "\n"


def resolve_inputs(run_dir: str) -> tuple[str, str]:
    """(trace path, metrics path), preferring the merged/aggregate
    artifacts of a multi-process run when present."""
    trace = os.path.join(run_dir, "trace.merged.jsonl")
    if not os.path.exists(trace):
        trace = os.path.join(run_dir, "trace.jsonl")
    prom = os.path.join(run_dir, "metrics.aggregate.prom")
    if not os.path.exists(prom):
        prom = os.path.join(run_dir, "metrics.prom")
    return trace, prom


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="Render a critical-path report from a --telemetry-dir "
                    "run (trace.jsonl + metrics.prom)")
    p.add_argument("run_dir", help="the run's --telemetry-dir")
    p.add_argument("--top", type=int, default=10,
                   help="span groups to show in the critical path")
    args = p.parse_args(argv)
    trace_path, prom_path = resolve_inputs(args.run_dir)
    if not os.path.exists(trace_path):
        print(f"no trace file under {args.run_dir} "
              f"(expected trace.jsonl — was the run started with "
              f"--telemetry-dir?)", file=sys.stderr)
        return 1
    spans = load_spans(trace_path)
    prom_text = ""
    if os.path.exists(prom_path):
        with open(prom_path, encoding="utf-8") as f:
            prom_text = f.read()
    sys.stdout.write(build_report(spans, prom_text, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
